"""shard-audit (tpu_paxos/analysis/shard_audit.py): the fifth tier.

Three layers under test.  The jax-free contract layer — partition-rule
matching (``parallel/partition_rules.py``) and the budget/certificate
judgments (``analysis/shard_rules.py``) — is exercised on crafted
inputs.  The audit layer proves RECALL the PR-7 way: each
``TPU_PAXOS_SHARD_WEDGE`` value arms one seeded regression and the
tier must fail NAMING it (the unruled leaf by pytree path, the
undeclared collective by (entry, mesh, opcode), the parity fork by
the first diverging (entry, mesh, lane)) — and pinning must refuse
while a wedge is armed.  The mesh-reshape layer is satellite-grade
end-to-end: a serve-fleet (lanes x rates) sweep must be bitwise
mesh-invariant — per-lane decision-log sha256 and the sweep verdict
identical between the unmeshed vmap and the 2-device tile.

Engine-cell budget: the wedge cells scope their providers to ONE
module and truncate the grid, so each pays at most two small
compiles.  The parity-fork wedge and the full (lanes x rates) sweep
ride the slow tier; their fast coverage is, respectively,
``test_check_certificate_mesh_invariance_names_first_lane`` (the
judgment the wedge must trip) and
``test_serve_sweep_mesh_reshape_parity_fast`` (the same comparison at
the one-cell shape).
"""

import hashlib
import os

import numpy as np
import pytest

from tpu_paxos.analysis import shard_audit as sha
from tpu_paxos.analysis import shard_rules as shr
from tpu_paxos.analysis.registry import RegistryError
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel import partition_rules as prules
from tpu_paxos.replay.decision_log import decision_log
from tpu_paxos.serve import fleet as sfl
from tpu_paxos.serve import harness as sh


# ---------------- partition rules (SH301 contract layer) ----------------

def test_match_path_first_rule_wins():
    # the sharded pend-queue leaf must hit its dedicated row, not the
    # ^sim/prop/ replicated catch-all sitting below it
    idx, dims = prules.match_path("sim/prop/pend")
    assert dims == (prules.LANE, None, None)
    cidx, cdims = prules.match_path("sim/prop/adopted_rounds")
    assert cdims == prules.REP and cidx > idx


def test_match_path_unmatched_is_none():
    assert prules.match_path("nosuchfamily/leaf") is None


def test_is_trivial_scalars_and_singletons():
    assert prules.is_trivial(np.int32(3))
    assert prules.is_trivial(np.zeros((1, 1)))
    assert not prules.is_trivial(np.zeros((2,)))


def test_rank_problem_exact_pin():
    # (None, LANE) pins rank 2 exactly — a rank-3 leaf means the rule
    # drifted from the state layout and must fail, not shard dim 1
    assert prules.rank_problem((None, prules.LANE), 2) is None
    msg = prules.rank_problem((None, prules.LANE), 3)
    assert msg and "rank 2" in msg and "rank 3" in msg


def test_rank_problem_open_rank():
    dims = (prules.LANE, Ellipsis)
    assert prules.rank_problem(dims, 1) is None
    assert prules.rank_problem(dims, 4) is None
    assert prules.rank_problem(dims, 0)  # fewer dims than the fixed prefix


def test_spec_of_substitutes_lane_axes():
    # LANE becomes the mesh's axis tuple; trailing ... maps to P()
    # padding (PartitionSpec is tuple-like, so no SH001-tripping
    # import is needed to compare)
    assert tuple(prules.spec_of(prules.REP, ("i",))) == ()
    assert tuple(prules.spec_of((None, prules.LANE), ("dcn", "i"))) == (
        None, ("dcn", "i"),
    )
    assert tuple(prules.spec_of((prules.LANE, Ellipsis), ("i",))) == (
        ("i",),
    )


def test_tree_spec_names_unruled_leaf_by_path():
    with pytest.raises(prules.PartitionRuleError, match="wedge/unruled"):
        prules.tree_spec("wedge", {"unruled": np.zeros((2, 2))}, ("i",))


def test_tree_spec_names_rank_drift():
    # fast/learned is ruled (None, LANE): feeding it rank 3 must name
    # the rule, not silently shard the wrong dimension
    with pytest.raises(prules.PartitionRuleError, match="fast/learned"):
        prules.tree_spec("fast", {"learned": np.zeros((2, 2, 2))}, ("i",))


def test_coverage_reports_stale_rules_and_unmatched():
    cov = prules.coverage({
        "e1": ("fast", {"learned": np.zeros((2, 4)),
                        "rogue": np.zeros((3,))}),
    })
    assert cov["leaves"] == 2
    assert [u["path"] for u in cov["unmatched"]] == ["fast/rogue"]
    assert not cov["rank"]
    # only the fast/learned row fired; every other committed row is
    # stale in this scoped sweep
    assert len(cov["stale_rules"]) == len(prules.RULES) - 1


# ---------------- shard_rules (SH302-304 contract layer) ----------------

def test_collective_census_folds_start_not_done():
    census = shr.collective_census({
        "all-reduce": 2, "all-reduce-start": 1, "all-reduce-done": 1,
        "fusion": 40,
    })
    assert census["all-reduce"] == 3
    assert census["all-gather"] == 0


def _cell(nbytes, **coll):
    c = {fam: 0 for fam in shr.COLLECTIVE_FAMILIES}
    c.update(coll)
    return {"bytes_per_device": nbytes, "collectives": c}


def test_check_budget_collectives_exact_both_directions():
    budget = {"backend": "cpu", "entries": {
        "e": {"1": _cell(9000, **{"all-reduce": 2})},
    }}
    over, _, _ = shr.check_budget(
        {"e": {"1": _cell(100, **{"all-reduce": 3})}}, budget, "cpu", False)
    under, _, _ = shr.check_budget(
        {"e": {"1": _cell(100, **{"all-reduce": 1})}}, budget, "cpu", False)
    for vs in (over, under):
        assert [(v["entry"], v["mesh"], v["key"]) for v in vs] == [
            ("e", 1, "all-reduce"),
        ]


def test_check_budget_bytes_ceiling_and_unpinned_cell():
    budget = {"backend": "cpu", "entries": {"e": {"1": _cell(9000)}}}
    violations, stale, enforced = shr.check_budget(
        {"e": {"1": _cell(9001), "2": _cell(10)}}, budget, "cpu", False)
    assert enforced
    assert {(v["mesh"], v["key"]) for v in violations} == {
        (1, "bytes_per_device"), (2, "budget"),
    }
    assert not stale


def test_check_budget_backend_gate():
    budget = {"backend": "tpu", "entries": {"e": {"1": _cell(1)}}}
    violations, stale, enforced = shr.check_budget(
        {"e": {"1": _cell(10**9)}}, budget, "cpu", True)
    assert (violations, stale, enforced) == ([], [], False)


def test_check_budget_stale_only_on_full_grid():
    budget = {"backend": "cpu", "entries": {
        "gone": {"1": _cell(9000)},
    }}
    _, stale_scoped, _ = shr.check_budget({}, budget, "cpu", False)
    _, stale_full, _ = shr.check_budget({}, budget, "cpu", True)
    assert stale_scoped == []
    assert stale_full == ["gone@mesh1"]


def test_first_divergence_orders_verdict_before_log():
    a = {"verdicts": "8f", "lane_logs": ["aa", "bb"]}
    assert shr.first_divergence(a, a) is None
    lane, detail = shr.first_divergence(
        a, {"verdicts": "8e", "lane_logs": ["aa", "bb"]})
    assert lane == 1 and "verdict" in detail
    lane, detail = shr.first_divergence(
        a, {"verdicts": "8f", "lane_logs": ["aa", "cc"]})
    assert lane == 1 and "sha256" in detail


def test_check_certificate_mesh_invariance_names_first_lane():
    # fast coverage for the slow parity-fork wedge: a mesh-2 run that
    # forks from its own mesh-1 run fails naming (entry, mesh, lane)
    # even with NOTHING pinned
    base = {"verdicts": "ff", "lane_logs": ["x", "y"]}
    fork = {"verdicts": "fe", "lane_logs": ["x", "y"]}
    fails = shr.check_certificate(
        {}, {"fleet.run_lanes": {"1": base, "2": fork}}, full=False)
    named = [(f["entry"], f["mesh"], f["lane"]) for f in fails]
    assert ("fleet.run_lanes", 2, 1) in named


def test_check_certificate_unpinned_and_stale():
    base = {"verdicts": "f", "lane_logs": ["x"]}
    fails = shr.check_certificate(
        {"entries": {"ghost": base}}, {"live": {"1": base}}, full=True)
    named = {(f["entry"], f["mesh"]) for f in fails}
    assert ("live", 1) in named      # no pin for the live entry
    assert ("ghost", None) in named  # pinned entry nothing produces


# ---------------- seeded wedges (audit-layer recall) ----------------

def test_unknown_wedge_value_rejected(monkeypatch):
    monkeypatch.setenv(shr.WEDGE_ENV, "bogus")
    with pytest.raises(ValueError, match="unknown"):
        sha.run_shard_audit(providers=(), budget_path=None, cert_path=None)


def test_pin_refuses_while_wedge_armed(monkeypatch):
    monkeypatch.setenv(shr.WEDGE_ENV, "parity-fork")
    with pytest.raises(RegistryError, match="enshrine"):
        sha.run_shard_audit(
            providers=(), budget_path=None, cert_path=None, pin=True)


def test_wedge_unruled_leaf_names_pytree_path(monkeypatch, tmp_path):
    monkeypatch.setenv(shr.WEDGE_ENV, "unruled-leaf")
    report = sha.run_shard_audit(
        providers=("tpu_paxos.parallel.sharded",),
        budget_path=None, cert_path=None,
        triage_dir=str(tmp_path), grid=(1,),
    )
    assert not report["ok"]
    assert [u["path"] for u in report["coverage"]["unmatched"]] == [
        "wedge/unruled",
    ]
    # the scoped run must not misread every unexercised rule as stale
    assert report["coverage"]["stale_rules"] == []


def test_wedge_undeclared_collective_names_entry_mesh_opcode(
        monkeypatch, tmp_path):
    monkeypatch.setenv(shr.WEDGE_ENV, "undeclared-collective")
    report = sha.run_shard_audit(
        providers=("tpu_paxos.parallel.sharded",),
        budget_path=shr.DEFAULT_BUDGET, cert_path=None,
        triage_dir=str(tmp_path), grid=(1, 2),
    )
    assert not report["ok"]
    named = [(v["entry"], v["mesh"], v["key"])
             for v in report["budget"]["violations"]]
    assert named == [("sharded.choose_all", 2, "collective-permute")]
    # the breached cell's compiled module is dumped for triage
    # (dump names flatten dots/@ to underscores)
    assert any("shard_sharded_choose_all" in d for d in report["dumped"])


@pytest.mark.slow
def test_wedge_parity_fork_names_first_diverging_lane(
        monkeypatch, tmp_path):
    # fast coverage: test_check_certificate_mesh_invariance_names_
    # first_lane judges the same comparison on crafted results
    monkeypatch.setenv(shr.WEDGE_ENV, "parity-fork")
    report = sha.run_shard_audit(
        providers=("tpu_paxos.fleet.runner",),
        budget_path=None, cert_path=shr.DEFAULT_CERT,
        triage_dir=str(tmp_path), grid=(1, 2),
    )
    assert not report["ok"]
    named = [(f["entry"], f["mesh"], f["lane"])
             for f in report["parity"]["failures"]]
    assert ("fleet.run_lanes", 2, 0) in named
    assert any(d.endswith(".json") for d in report["dumped"])


def test_usable_grid_truncates_to_host_devices():
    grid = sha.usable_grid((1, 2, 4, 8, 16))
    assert grid == (1, 2, 4, 8)  # conftest provisions 8 virtual devices


# ---------------- mesh axis hygiene (satellite) ----------------

def test_shard_map_rejects_foreign_axis_names():
    mesh = pmesh.make_instance_mesh(1)
    bogus = prules.spec_of((prules.LANE,), "bogus")
    with pytest.raises(ValueError, match="the mesh has axes"):
        pmesh.shard_map(
            lambda x: x, mesh, in_specs=(bogus,), out_specs=bogus)


# ---------------- serve-fleet mesh-reshape parity (satellite) -------

_CFG = SimConfig(
    n_nodes=3, n_instances=48, proposers=(0, 1), seed=3,
    max_rounds=4000,
    faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
)
_SLO = sh.ServeSLO(latency_rounds=128, budget_milli=150)


def _lane_shas(rep):
    out = []
    for i in range(rep.n_lanes):
        cv, cb = rep.lane_chosen(i)
        text = decision_log(cv, cb, stride=30, n_instances=len(cv))
        out.append(hashlib.sha256(text.encode()).hexdigest())
    return out


def _point_key(pt):
    """The deterministic slice of a sweep point (values_per_sec is
    wall-clock and may not be compared across runs)."""
    return (pt["rate_milli"], pt["lanes"], pt["decided"], pt["backlog"],
            pt["done"], pt["rounds"], pt["dispatches"], pt["sustained"],
            pt["p50"], pt["p99"], tuple(pt["breach_lanes"]),
            pt["shed"], tuple(pt["lane_shed"]), pt["control_decisions"])


def _mesh_reshape_parity(n_values, lane_counts, rates):
    """Run the CONTROLLED (lanes x rates) grid unmeshed and on the
    2-device tile: per-lane decision-log shas, the shed/decision
    ledgers, the deterministic point fields, and the sweep verdict
    must all be bitwise identical — the controller consumes the
    on-device breach vector, so this is the strongest mesh-invariance
    claim the serve stack makes.  The geometry IS test_control's
    module shape (2-lane, S=2, K=10, W=32, the _cfg(3) engine cell,
    default policy): in a full-suite run the unmeshed 2-lane
    executable is already warm, so the fast cell pays only the one
    mesh-2 tile compile; direct runs and sweep cells share every
    executable."""
    from tpu_paxos.serve import control as ctlm

    mesh2 = pmesh.make_instance_mesh(2)
    geom = dict(rounds_per_window=8, windows_per_dispatch=2,
                window_rounds=32, slo=_SLO)
    width = max(10, sfl.grid_admit_width(
        _CFG, n_values, lane_counts, rates, rounds_per_window=8))
    for lc in lane_counts:
        for rm in rates:
            lanes = sfl.fleet_lanes(_CFG, lc, n_values, rm, 0)
            reps = [
                ctlm.controlled_fleet_run(
                    _CFG, lanes, control=ctlm.ControlPolicy(),
                    admit_width=width, mesh=m, **geom)
                for m in (None, mesh2)
            ]
            assert _lane_shas(reps[0]) == _lane_shas(reps[1])
            assert list(reps[0].decided) == list(reps[1].decided)
            assert list(reps[0].breach) == list(reps[1].breach)
            assert reps[0].shed_total == reps[1].shed_total
            assert reps[0].lane_shed == reps[1].lane_shed
            assert len(reps[0].decisions) == len(reps[1].decisions)
    sweeps = [
        sfl.sweep_fleet_load(
            _CFG, n_values, lane_counts, rates,
            admit_width=width, control=ctlm.ControlPolicy(),
            mesh=m, **geom)
        for m in (None, mesh2)
    ]
    assert sfl.sweep_verdict(sweeps[0]) == sfl.sweep_verdict(sweeps[1])
    for lc in lane_counts:
        a = sweeps[0]["cells"][str(lc)]["points"]
        b = sweeps[1]["cells"][str(lc)]["points"]
        assert [_point_key(p) for p in a] == [_point_key(p) for p in b]


def test_serve_sweep_mesh_reshape_parity_fast():
    # one-cell shape: the executables here warm the slow grid's (2,)
    # lane count too
    _mesh_reshape_parity(12, (2,), (4000,))


@pytest.mark.slow
def test_serve_sweep_mesh_reshape_parity_full_grid():
    # fast coverage: test_serve_sweep_mesh_reshape_parity_fast runs
    # the same comparison at the (2 lanes x 4000 milli) cell
    _mesh_reshape_parity(24, (2, 4), (2000, 4000))


# ---------------- committed artifacts stay judgeable ----------------

def test_committed_budget_and_certificate_parse():
    budget = shr.load_budget()
    cert = shr.load_certificate()
    assert budget["entries"] and cert["entries"]
    for name, per_mesh in budget["entries"].items():
        for mesh, cell in per_mesh.items():
            int(mesh)
            assert cell["bytes_per_device"] > 0
            assert set(cell["collectives"]) <= set(shr.COLLECTIVE_FAMILIES)
    for name, e in cert["entries"].items():
        assert len(e["verdicts"]) == len(e["lane_logs"])
        assert all(len(s) == 64 for s in e["lane_logs"])
    assert os.path.basename(shr.DEFAULT_BUDGET) == "shard_budget.json"
