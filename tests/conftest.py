"""Test configuration: force an 8-device virtual CPU platform.

Real multi-chip hardware is not available in CI; sharding correctness
is validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun does.  Note: this environment preloads jax via sitecustomize
with the TPU platform selected, so env vars are too late — the
platform must be switched through jax.config before any backend
initialization (first device/array use).
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the option landed after 0.4.x; the XLA flag does the
    # same provisioning as long as the backend is not initialized yet
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
jax.config.update("jax_threefry_partitionable", True)
