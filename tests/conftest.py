"""Test configuration: force an 8-device virtual CPU platform.

Real multi-chip hardware is not available in CI; sharding correctness
is validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun does (xla_force_host_platform_device_count).  This must run
before jax initializes, hence top of conftest.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
