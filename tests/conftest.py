"""Test configuration: force an 8-device virtual CPU platform.

Real multi-chip hardware is not available in CI; sharding correctness
is validated on a virtual 8-device CPU mesh exactly as the driver's
dryrun does.  Note: this environment preloads jax via sitecustomize
with the TPU platform selected, so env vars are too late — the
platform must be switched through jax.config before any backend
initialization (first device/array use).
"""

import os

import jax
import pytest

# paxlint: allow[DET004] platform selection for the test mesh, value-neutral
jax.config.update("jax_platforms", "cpu")
try:
    # paxlint: allow[DET004] device provisioning, value-neutral
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the option landed after 0.4.x; the XLA flag does the
    # same provisioning as long as the backend is not initialized yet
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
# The threefry pin lives in utils/prng (DET004's one sanctioned home
# for value-affecting flags); importing it applies the pin.
from tpu_paxos.utils import prng as _prng  # noqa: E402,F401

# ---- compile-census guard (tpu_paxos/analysis/tracecount.py) ----
# Counts every XLA compilation and attributes it to the test module
# that triggered it; pytest_sessionfinish enforces the pinned
# per-module budget for full tier-1-shaped runs, so a retrace
# regression fails CI with a named culprit instead of just slowing
# the suite down.
from tpu_paxos.analysis import tracecount  # noqa: E402

_census = tracecount.CompileCensus().start()


def pytest_runtest_setup(item):
    _census.set_label(item.location[0])


@pytest.fixture
def compile_census():
    """The session's live CompileCensus (tests can read .counts or
    run their own scoped census on top — listeners stack)."""
    return _census


def _census_applicable(config) -> bool:
    """Budgets were pinned from the tier-1 suite (-m 'not slow', no
    -k, default compile options): only an equivalent selection
    produces comparable counts — in-process jit caches make module
    counts order-dependent, and debug modes compile different
    programs."""
    return (
        getattr(config.option, "markexpr", "") == "not slow"
        and not getattr(config.option, "keyword", "")
        and not os.environ.get("JAX_DEBUG_NANS")
        and not os.environ.get("JAX_DISABLE_JIT")
    )


def pytest_sessionfinish(session, exitstatus):
    pin = os.environ.get("TPU_PAXOS_COMPILE_CENSUS_PIN")
    if pin:
        # re-pin the budget from this run's measured counts (the
        # intentional-change workflow; see README) — but only from a
        # run whose counts a future tier-1 session will actually be
        # comparable to: passing, tier-1-shaped, default compile opts
        if exitstatus != 0 or not _census_applicable(session.config):
            print(
                f"\ncompile census NOT pinned to {pin}: pinning needs "
                "a PASSING tier-1-shaped run (-m 'not slow', no -k, "
                "no debug-NaNs/disable-jit) — partial or failing "
                "sessions measure different jit-cache state"
            )
            return
        tracecount.save_budget(_census.counts, pin, visited=_census.visited)
        print(
            f"\ncompile census pinned to {pin} "
            f"({len(_census.visited)} modules visited)"
        )
        print(_census.report())
        return
    budget = tracecount.load_budget(
        os.environ.get("TPU_PAXOS_COMPILE_BUDGET", tracecount.DEFAULT_BUDGET)
    )
    if not budget:
        return
    forced = os.environ.get("TPU_PAXOS_COMPILE_CENSUS", "") == "1"
    if not _census.should_enforce(budget):
        # a tier-1-shaped run that still can't enforce means budgeted
        # modules were never visited (renamed/deleted/slow-marked):
        # say so — a silently disarmed guard is how regressions land
        # (test_tracecount also fails on budget entries whose file is
        # gone, so CI stays red until the budget is re-pinned)
        whole_suite = getattr(
            session.config.option, "file_or_dir", []
        ) in ([], ["tests"], ["tests/"])
        # only warn for PASSING whole-suite runs: a failed -x session
        # skips later modules for a reason the failure already explains
        if (exitstatus == 0 and whole_suite
                and _census_applicable(session.config)):
            missing = sorted(set(budget.get("budgets", {})) - _census.visited)
            if missing:
                print(
                    "\ncompile-census NOT enforced: budgeted modules "
                    f"never visited this run: {', '.join(missing[:5])}"
                    f"{' …' if len(missing) > 5 else ''} — re-pin "
                    "compile_budget.json if they were renamed/removed"
                )
        return
    if not forced and not _census_applicable(session.config):
        return
    violations = _census.check_budget(budget)
    if violations:
        print("\ncompile-census budget EXCEEDED:")
        for v in violations:
            print(f"  {v}")
        print(_census.report())
        session.exitstatus = 1
