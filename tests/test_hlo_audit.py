"""hlo-audit: compiled-artifact contracts — normalizer, donation
checker, per-primitive budgets + memory ceilings, goldens, bounded
triage dumps, CLI.

Three layers, mirroring tests/test_jaxpr_audit.py:

- **Normalizer contract** (pure text + one cheap real entry): the
  same entry lowered twice normalizes byte-identically; a metadata /
  value-numbering perturbation normalizes away; a structural change
  does not.
- **Fixture layer** (tests/data/hlo_fixture.py): the two seeded
  regressions — a dropped ``donate_argnums`` behind a flag (the
  aliasing checker must fail naming entry + parameter) and an
  injected dtype widening (per-primitive budget AND golden diff must
  fail, diff dumped to the triage dir).
- **Repo + CLI layer**: the cheap registered entries fast-tier
  against the committed pins; the full golden sweep (every entry
  compiled, ~2 min) slow-tier; CLI e2e asserting exit codes and
  named culprits.
"""

import gzip
import json
import os
import subprocess
import sys
import time

import pytest

from tpu_paxos.analysis import hlo_audit, hlo_norm, jaxpr_audit, triage
from tpu_paxos.analysis import registry as regm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "data", "hlo_fixture.py")

#: Cheap registered providers (sub-second compiles) — the fast-tier
#: slice of the repo audit; the full registry runs slow-tier.
CHEAP_PROVIDERS = (
    "tpu_paxos.core.fast",
    "tpu_paxos.core.simkern",
    "tpu_paxos.core.fastwin",
)

RAW = """\
HloModule jit_f, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={(s32[8]{0})->s32[8]{0}}

%fused_computation.123 (param_0.7: s32[8], param_1.9: s32[8]) -> s32[8] {
  %param_0.7 = s32[8]{0} parameter(0)
  %param_1.9 = s32[8]{0} parameter(1)
  ROOT %add.991 = s32[8]{0} add(s32[8]{0} %param_0.7, s32[8]{0} %param_1.9), metadata={op_name="jit(f)/add" source_file="/x/y.py" source_line=7}
}

ENTRY %main.42 (p0.1: s32[8], p1.2: s32[8]) -> s32[8] {
  %p0.1 = s32[8]{0} parameter(0)
  %p1.2 = s32[8]{0} parameter(1)
  %copy.17 = s32[8]{0} copy(s32[8]{0} %p1.2), metadata={op_name="x{y}" source_file="/x/y.py" source_line=9}
  ROOT %fusion.5 = s32[8]{0} fusion(s32[8]{0} %p0.1, /*index=1*/s32[8]{0} %copy.17), kind=kLoop, calls=%fused_computation.123
}
"""


# ---------------- normalizer (pure text) ----------------

def test_normalize_strips_noise_and_renumbers():
    norm = hlo_norm.normalize(RAW)
    # header: only the module name + alias table survive
    assert norm.splitlines()[0] == (
        "HloModule jit_f, input_output_alias="
        "{{0}: (0, {}, may-alias), {1}: (2, {}, may-alias)}"
    )
    assert "metadata=" not in norm
    assert "source_line" not in norm
    assert "is_scheduled" not in norm
    assert "entry_computation_layout" not in norm
    assert "/*index=" not in norm
    assert "{0}" in norm.splitlines()[0]  # alias tuple kept
    assert "s32[8]{0}" not in norm  # layouts stripped
    # ids renumbered from 0 in first-appearance order
    assert "%fused_computation.0" in norm
    assert "%add.0" in norm and "%add.991" not in norm
    # the signature's bare (un-sigiled) param ids renumber too
    assert "param_0.7" not in norm


def test_normalize_value_numbering_is_first_appearance_stable():
    import re

    bumped = re.sub(
        r"(%?[A-Za-z_][\w-]*)\.(\d+)",
        lambda m: f"{m.group(1)}.{int(m.group(2)) + 1000}", RAW,
    )
    assert hlo_norm.normalize(bumped) == hlo_norm.normalize(RAW)


def test_normalize_metadata_perturbation_normalizes_away():
    pert = RAW.replace("source_line=7", "source_line=12345")
    assert hlo_norm.normalize(pert) == hlo_norm.normalize(RAW)


def test_normalize_structural_change_survives():
    # an extra convert is a real program change, not noise
    lines = RAW.splitlines()
    idx = next(i for i, l in enumerate(lines) if "%copy.17" in l)
    lines.insert(
        idx, "  %convert.3 = f32[8]{0} convert(s32[8]{0} %p1.2)"
    )
    assert hlo_norm.normalize("\n".join(lines)) != hlo_norm.normalize(RAW)


def test_strip_attr_is_quote_and_brace_aware():
    # op_name carries braces inside the quoted string (jaxpr params
    # leak into provenance) — the stripper must not stop early
    line = (
        '  %a.1 = s32[] add(%b.2, %c.3), '
        'metadata={op_name="while[body={x}]" source_file="f.py"}, '
        'backend_config="cfg"'
    )
    out = hlo_norm._strip_attr(line, "metadata")
    assert "metadata" not in out
    assert 'backend_config="cfg"' in out


def test_opcode_histogram_and_summary():
    hist = hlo_norm.opcode_histogram(hlo_norm.normalize(RAW))
    assert hist["add"] == 1
    assert hist["copy"] == 1
    assert hist["fusion"] == 1
    assert hist["parameter"] == 4
    summary = hlo_norm.histogram_summary(
        {"fusion": 2, "copy": 1, "copy-start": 3, "copy-done": 3,
         "while": 1, "add": 5}
    )
    assert summary == {
        "hlo_ops": 15, "fusion": 2, "copy": 7, "convert": 0,
        "transpose": 0, "while": 1,
    }


def test_alias_table_parses_nested_braces():
    assert hlo_norm.alias_table(RAW) == [
        {"output": (0,), "param": 0, "kind": "may-alias"},
        {"output": (1,), "param": 2, "kind": "may-alias"},
    ]
    assert hlo_norm.aliased_params(RAW) == {0, 2}
    assert hlo_norm.alias_table("HloModule jit_g\n") == []


# ---------------- normalizer (real lowering) ----------------

def _lower_text(entry) -> str:
    lowered, _args = hlo_audit.lower_entry(entry)
    return lowered.compile().as_text() or ""


@pytest.fixture(scope="module")
def fast_entry():
    from tpu_paxos.core import fast

    (entry,) = fast.audit_entries()
    return entry


def test_same_entry_lowered_twice_normalizes_identically(fast_entry):
    t1 = hlo_norm.normalize(_lower_text(fast_entry))
    t2 = hlo_norm.normalize(_lower_text(fast_entry))
    assert t1 == t2


# ---------------- donation checker ----------------

def test_expected_donated_params_pytree_offsets():
    import jax.numpy as jnp

    state = {"a": jnp.zeros((4,), jnp.int32),
             "b": jnp.zeros((4,), jnp.int32)}
    x = jnp.zeros((4,), jnp.int32)
    # donate arg 1: its leaves sit after arg 0's two leaves
    exp = hlo_audit.expected_donated_params((state, x), (1,))
    assert sorted(exp) == [2]
    exp = hlo_audit.expected_donated_params((state, x), (0,))
    assert sorted(exp) == [0, 1]
    # a non-array leaf before the donated arg breaks the numbering
    with pytest.raises(regm.RegistryError, match="all-array"):
        hlo_audit.expected_donated_params((3, state), (1,))


def test_fastwin_entry_donation_is_aliased():
    # the real donated surface: every FastState leaf must alias
    from tpu_paxos.core import fastwin

    (entry,) = fastwin.audit_entries()
    lowered, args = hlo_audit.lower_entry(entry)
    text = lowered.compile().as_text()
    assert hlo_audit.check_donation(entry, args, text) == []
    expected = hlo_audit.expected_donated_params(
        args, entry.donate_argnums
    )
    assert set(expected) <= hlo_norm.aliased_params(text)
    assert len(expected) == 5  # the five FastState leaves


def test_seeded_dropped_donation_fails_named(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_PAXOS_HLO_FIXTURE_DROP_DONATION", "1")
    provs = jaxpr_audit._load_provider_arg(FIXTURE)
    report = hlo_audit.run_hlo_audit(
        providers=provs, budget_path=None,
        goldens_dir=str(tmp_path / "hlo"),
        triage_dir=str(tmp_path / "triage"),
    )
    assert not report["ok"]
    assert [(d["entry"], d["param"]) for d in report["donation"]] == [
        ("hlofix.donated", 0), ("hlofix.donated", 1),
    ]
    assert "dropped" in report["donation"][0]["detail"]


def test_fixture_clean_donation_passes(tmp_path):
    provs = jaxpr_audit._load_provider_arg(FIXTURE)
    report = hlo_audit.run_hlo_audit(
        providers=provs, budget_path=None,
        goldens_dir=str(tmp_path / "hlo"),
        triage_dir=str(tmp_path / "triage"),
    )
    assert report["ok"], report["donation"]
    assert report["entries"]["hlofix.donated"]["aliased_params"] == [0, 1]


# ---------------- budgets + goldens (fixture) ----------------

def test_seeded_widening_breaches_budget_and_golden(tmp_path, monkeypatch):
    bud = str(tmp_path / "hlo_budget.json")
    gold = str(tmp_path / "hlo")
    tri = str(tmp_path / "triage")
    provs = jaxpr_audit._load_provider_arg(FIXTURE)
    # pin the clean fixture, judge it clean
    rep = hlo_audit.run_hlo_audit(
        providers=provs, budget_path=bud, goldens_dir=gold, pin=True,
        triage_dir=tri,
    )
    rep = hlo_audit.run_hlo_audit(
        providers=provs, budget_path=bud, goldens_dir=gold,
        triage_dir=tri,
    )
    assert rep["ok"], rep["budget"]["violations"]
    assert rep["entries"]["hlofix.widen"]["golden"] == "ok"
    # arm the seeded regression
    monkeypatch.setenv("TPU_PAXOS_HLO_FIXTURE_WIDEN", "1")
    provs = jaxpr_audit._load_provider_arg(FIXTURE)
    rep = hlo_audit.run_hlo_audit(
        providers=provs, budget_path=bud, goldens_dir=gold,
        triage_dir=tri,
    )
    assert not rep["ok"]
    by_key = {(v["entry"], v["key"]) for v in rep["budget"]["violations"]}
    assert ("hlofix.widen", "convert") in by_key   # per-primitive cap
    assert ("hlofix.widen", "golden") in by_key    # golden diff
    assert rep["entries"]["hlofix.widen"]["golden"] == "mismatch"
    # breach artifacts: unified diff + compiled text, deterministic names
    diff = os.path.join(tri, "hlo_hlofix_widen.diff")
    txt = os.path.join(tri, "hlo_hlofix_widen.txt")
    assert os.path.exists(diff) and os.path.exists(txt)
    body = open(diff, encoding="utf-8").read()
    assert "golden/hlofix.widen" in body and "convert" in body


def test_budget_backend_gate_and_staleness():
    measured = {"e.one": {"hlo_ops": 10, "convert": 1, "mem_bytes": 100}}
    budget = {
        "version": 1, "backend": "quantum",
        "entries": {"e.one": {"hlo_ops": 1}},
    }
    v, stale, enforced = hlo_audit.check_budget(measured, budget, "cpu")
    assert not enforced and not v and not stale  # wrong backend: gated
    # an empty budget (deleted file) is NOT a silent pass
    v, stale, enforced = hlo_audit.check_budget(measured, {}, "cpu")
    assert enforced and [x["cap"] for x in v] == [None]
    budget["backend"] = "cpu"
    v, stale, enforced = hlo_audit.check_budget(measured, budget, "cpu")
    assert enforced
    assert [x["key"] for x in v] == ["hlo_ops"]
    # unpinned entries are violations; retired names are stale
    v2, stale2, _ = hlo_audit.check_budget(
        {"e.new": {"hlo_ops": 3}}, budget, "cpu"
    )
    assert v2[0]["cap"] is None and "no pinned" in v2[0]["detail"]
    assert stale2 == ["e.one"]


def test_save_budget_caps_with_headroom_and_slack(tmp_path):
    path = str(tmp_path / "b.json")
    measured = {"e": {"hlo_ops": 100, "convert": 0, "mem_bytes": 1000}}
    data = hlo_audit.save_budget(measured, path, "cpu", "x.y.z")
    caps = data["entries"]["e"]
    assert caps["hlo_ops"] == int(100 * 1.25) + 2
    assert caps["convert"] == 2  # zero pins at the slack floor
    assert caps["mem_bytes"] == int(1000 * 1.3) + 4096
    assert json.load(open(path))["backend"] == "cpu"


def test_save_golden_bytes_are_deterministic(tmp_path):
    gold = str(tmp_path)
    p1 = hlo_audit.save_golden("a.b", "HloModule x\n", gold)
    b1 = open(p1, "rb").read()
    time.sleep(0.05)  # a second save must not embed the new mtime
    p2 = hlo_audit.save_golden("a.b", "HloModule x\n", gold)
    assert open(p2, "rb").read() == b1
    assert hlo_audit.load_golden("a.b", gold) == "HloModule x\n"
    assert hlo_audit.load_golden("missing", gold) is None
    with gzip.open(p1, "rt", encoding="utf-8") as fh:
        assert fh.read() == "HloModule x\n"


# ---------------- bounded triage dumps ----------------

def test_dump_names_are_deterministic():
    assert triage.dump_name("hlo", "sim.run_rounds", "diff") == (
        "hlo_sim_run_rounds.diff"
    )
    assert triage.dump_name("jaxpr", "fleet.run_lanes") == (
        "jaxpr_fleet_run_lanes.txt"
    )


def test_write_dump_overwrites_not_accumulates(tmp_path):
    d = str(tmp_path)
    p1 = triage.write_dump(d, "hlo", "e.same", "one")
    p2 = triage.write_dump(d, "hlo", "e.same", "two")
    assert p1 == p2
    assert os.listdir(d) == ["hlo_e_same.txt"]
    assert open(p2).read() == "two"


def test_retention_cap_prunes_oldest_analysis_dumps(tmp_path):
    d = str(tmp_path)
    # a stress repro artifact shares the dir but not the namespace
    repro = os.path.join(d, "repro_fleet_g0_lane0.json")
    open(repro, "w").write("{}")
    for i in range(triage.RETENTION_CAP + 8):
        p = triage.write_dump(d, "jaxpr", f"e.n{i:03d}", "x")
        os.utime(p, (1_000_000 + i, 1_000_000 + i))
    dumps = [n for n in os.listdir(d) if n.startswith("jaxpr_")]
    assert len(dumps) == triage.RETENTION_CAP
    # oldest pruned first: the survivors are the newest CAP dumps
    assert f"jaxpr_e_n{0:03d}.txt" not in dumps
    assert f"jaxpr_e_n{triage.RETENTION_CAP + 7:03d}.txt" in dumps
    assert os.path.exists(repro)  # repro artifacts never pruned


# ---------------- repo pins ----------------

def test_cheap_repo_entries_within_committed_pins():
    # the sub-second slice of the registry, enforced fast-tier against
    # the committed budget + goldens (simkern + fastwin are
    # golden-pinned; scoped runs skip staleness by design)
    report = hlo_audit.run_hlo_audit(providers=CHEAP_PROVIDERS)
    assert report["ok"], json.dumps(
        {k: report[k] for k in ("donation", "budget")}, indent=1,
        sort_keys=True, default=str,
    )
    assert report["entries"]["fastwin.steady_windows"]["golden"] == "ok"
    assert report["entries"]["simkern.store_accepts"]["golden"] == "ok"


@pytest.mark.slow
def test_repo_hlo_audit_green():
    # every registered entry compiled and judged against the committed
    # hlo_budget.json + tests/data/hlo goldens (~2 min)
    report = hlo_audit.run_hlo_audit()
    assert report["ok"], json.dumps(
        {k: report[k] for k in ("donation", "budget")}, indent=1,
        sort_keys=True, default=str,
    )
    golden = [n for n, e in sorted(report["entries"].items())
              if e["golden"] != "-"]
    assert len(golden) == 10 and all(
        report["entries"][n]["golden"] == "ok" for n in golden
    ), {n: report["entries"][n]["golden"] for n in golden}


# ---------------- CLI (subprocess) ----------------

def _audit(args, env_extra=None, cwd=REPO):
    from _subproc import scrubbed_env

    env = scrubbed_env(
        extra_prefixes=("TPU_PAXOS_OP_BUDGET", "TPU_PAXOS_HLO"),
        JAX_PLATFORMS="cpu", **(env_extra or {}),
    )
    return subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "audit", *args],
        capture_output=True, text=True, timeout=500, cwd=cwd, env=env,
    )


def test_cli_dropped_donation_e2e():
    p = _audit(
        ["--hlo-only", "--no-budget", "--providers",
         "tests/data/hlo_fixture.py"],
        env_extra={"TPU_PAXOS_HLO_FIXTURE_DROP_DONATION": "1"},
    )
    assert p.returncode == 1, p.stdout + p.stderr[-2000:]
    assert "hlofix.donated" in p.stdout
    assert "donated parameter" in p.stdout
    assert "1 donation violations" not in p.stdout  # both params named
    assert "2 donation violations" in p.stdout


@pytest.mark.slow
def test_cli_widening_e2e_with_triage_dump(tmp_path):
    bud = str(tmp_path / "hlo_budget.json")
    gold = str(tmp_path / "hlo")
    tri = str(tmp_path / "triage")
    base = ["--hlo-only", "--providers", "tests/data/hlo_fixture.py",
            "--hlo-budget", bud, "--hlo-goldens", gold,
            "--triage-dir", tri]
    p = _audit(base + ["--pin"])
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    p = _audit(base)
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    p = _audit(base, env_extra={"TPU_PAXOS_HLO_FIXTURE_WIDEN": "1"})
    assert p.returncode == 1, p.stdout + p.stderr[-2000:]
    assert "hlofix.widen" in p.stdout and "convert" in p.stdout
    assert "drifted from the pinned golden" in p.stdout
    assert os.path.exists(os.path.join(tri, "hlo_hlofix_widen.diff"))


@pytest.mark.slow
def test_cli_full_audit_with_hlo_exits_zero():
    # what `make audit` runs: both tiers over the full registry
    p = _audit(["--hlo"])
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    assert "0 budget violations" in p.stdout
    assert "0 donation violations" in p.stdout
