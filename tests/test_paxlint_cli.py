"""paxlint CLI surface: golden-JSON output, exit codes, and the
jax-free import guard.

The golden test pins the machine-readable report byte-for-byte
(tests/data/paxlint_golden.json): the JSON schema is an interface —
CI consumers parse it — so any change must be deliberate enough to
update the golden file.

The jax-free guard purges jax from ``sys.modules`` and blocks
re-import, then runs the FULL repo lint in that subprocess: the
analysis subpackage must keep the same lazy-import discipline as
``core/__init__.py`` (``make lint`` runs in seconds on jax-less CI
images)."""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "paxlint_golden.json")

FIXTURE = '''\
"""paxlint golden fixture: one finding per family + one pragma."""
import json
import time

import jax


def set_flags():
    jax.config.update("jax_threefry_partitionable", False)


def emit(stream, summary, members):
    stream.write(f"[{time.time()}] start")
    for m in set(members):
        stream.write(str(m))
    print(json.dumps(summary))


@jax.jit
def step(state):
    if state > 0:
        return state
    return -state


@jax.jit
def allowed(state):
    if state > 0:  # paxlint: allow[JAX101] demo suppression
        return state
    return -state
'''


def _env():
    from _subproc import scrubbed_env

    return scrubbed_env()


def _lint(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tpu_paxos", "lint", *args],
        capture_output=True, text=True, timeout=120, cwd=cwd, env=_env(),
    )


def test_cli_golden_json(tmp_path):
    (tmp_path / "fixture.py").write_text(FIXTURE)
    p = _lint(
        ["--json", "--no-baseline", "--root", str(tmp_path), "fixture.py"],
        cwd=REPO,
    )
    assert p.returncode == 1, p.stderr[-2000:]  # findings present
    got = json.loads(p.stdout)
    with open(GOLDEN, encoding="utf-8") as fh:
        want = json.load(fh)
    assert got == want, (
        "paxlint JSON report drifted from tests/data/paxlint_golden.json"
        " — if intentional, regenerate via the command in that file's"
        " sibling README note\n" + json.dumps(got, indent=1, sort_keys=True)
    )


def test_cli_repo_is_clean_and_exits_zero():
    p = _lint([], cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    assert "0 findings" in p.stdout


def test_cli_rules_listing():
    p = _lint(["--rules"], cwd=REPO)
    assert p.returncode == 0
    for rid in ("DET001", "DET002", "DET003", "DET004",
                "JAX101", "JAX102", "JAX103", "JAX104"):
        assert rid in p.stdout


def test_cli_stale_baseline_fails(tmp_path):
    # unscoped run (default package walk): a baseline entry for a file
    # that no longer produces the finding must fail as stale.  (A
    # path-scoped run deliberately skips out-of-selection entries —
    # covered in test_paxlint.py.)
    pkg = tmp_path / "tpu_paxos"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n")
    stale = tmp_path / "baseline.json"
    stale.write_text(json.dumps({
        "version": 1,
        "entries": [{"rule": "DET001", "file": "tpu_paxos/gone.py",
                     "count": 3}],
    }))
    p = _lint(
        ["--root", str(tmp_path), "--baseline", str(stale)], cwd=REPO,
    )
    assert p.returncode == 1
    assert "stale" in p.stdout


def test_cli_missing_path_exits_2(tmp_path):
    p = _lint(["--root", str(tmp_path), "no_such.py"], cwd=REPO)
    assert p.returncode == 2
    assert "does not exist" in p.stdout


JAXFREE_DRIVER = textwrap.dedent("""\
    import builtins, sys

    # purge any preloaded jax (this container's sitecustomize pulls it
    # in), then forbid re-import: analysis must never need it
    for m in [m for m in sys.modules
              if m.split(".")[0] in ("jax", "jaxlib")]:
        del sys.modules[m]
    _real = builtins.__import__

    def _imp(name, *a, **k):
        if name.split(".")[0] in ("jax", "jaxlib"):
            raise ImportError("jax import forbidden in analysis: " + name)
        return _real(name, *a, **k)

    builtins.__import__ = _imp
    from tpu_paxos.analysis import artifact_schema, lint, rules_det, rules_jax
    report = lint.run_lint(root="@@ROOT@@")
    assert report["ok"], report
    art = {"format": artifact_schema.ARTIFACT_FORMAT}
    try:
        artifact_schema.validate_artifact(art)
    except artifact_schema.ArtifactSchemaError as e:
        assert e.field == "cfg", e
    print("JAXFREE_OK", report["baselined"])
""")


def test_analysis_imports_and_lints_without_jax():
    p = subprocess.run(
        [sys.executable, "-c", JAXFREE_DRIVER.replace("@@ROOT@@", REPO)],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=_env(),
    )
    assert p.returncode == 0, p.stderr[-3000:]
    assert "JAXFREE_OK" in p.stdout


# ---------------- --fix scaffolding (CLI surface) ----------------

FIX_FIXTURE = '''\
import time


def emit(stream, members):
    for m in set(members):
        stream.write(str(m))
    stream.write(str(time.time()))
'''


def test_cli_fix_dry_run_prints_diff_and_leaves_tree(tmp_path):
    (tmp_path / "fixture.py").write_text(FIX_FIXTURE)
    p = _lint(
        ["--fix", "--no-baseline", "--root", str(tmp_path), "fixture.py"],
        cwd=REPO,
    )
    assert p.returncode == 1, p.stderr[-2000:]  # findings still present
    assert "+    for m in sorted(set(members)):" in p.stdout
    assert "+    # paxlint: allow[DET001]" in p.stdout
    assert "dry run" in p.stdout
    # dry run never writes
    assert (tmp_path / "fixture.py").read_text() == FIX_FIXTURE


def test_cli_fix_write_applies_and_relints_clean(tmp_path):
    (tmp_path / "fixture.py").write_text(FIX_FIXTURE)
    p = _lint(
        ["--fix", "--write", "--no-baseline", "--root", str(tmp_path),
         "fixture.py"],
        cwd=REPO,
    )
    assert p.returncode == 1, p.stderr[-2000:]
    assert "fixed: fixture.py" in p.stdout
    fixed = (tmp_path / "fixture.py").read_text()
    assert "sorted(set(members))" in fixed
    assert "# paxlint: allow[DET001] TODO:" in fixed
    p = _lint(
        ["--no-baseline", "--root", str(tmp_path), "fixture.py"],
        cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    assert "0 findings" in p.stdout


def test_cli_write_without_fix_is_an_error(tmp_path):
    p = _lint(["--write", "--root", str(tmp_path)], cwd=REPO)
    assert p.returncode == 2
    assert "--write requires --fix" in p.stderr


def test_cli_fix_with_json_is_an_error(tmp_path):
    # --fix's output is the diff; silently dropping --json would hand
    # a JSON consumer human text — refuse loudly instead
    p = _lint(["--fix", "--json", "--root", str(tmp_path)], cwd=REPO)
    assert p.returncode == 2
    assert "--fix does not support --json" in p.stderr
