"""The bench's artifact-proofing machinery (VERDICT r4 #1): the
roofline guard must withhold physically impossible timings, the
chosen-count check must be a real raise (not a strippable assert),
and a non-converged median run must never publish an overstated
value.  BENCH_r04 recorded a ~2000x timing artifact; these pin the
defenses that keep one from ever landing in a BENCH file again."""

import jax.numpy as jnp
import numpy as np
import pytest

import bench
from tpu_paxos.config import SimConfig
from tpu_paxos.core import sim as simm
from tpu_paxos.utils import prng


def test_implausible_trips_on_impossible_bandwidth():
    # 1 GiB of state traffic in 1 microsecond is ~1e15 B/s — far over
    # any single chip
    msg = bench._implausible(1 << 30, 1e-6)
    assert msg is not None and "roofline" in msg


def test_implausible_accepts_real_bandwidth():
    # 1 GiB in 10 ms is ~107 GB/s — fine on a v5e
    assert bench._implausible(1 << 30, 0.010) is None


def test_implausible_scales_with_devices():
    # 8 devices legitimately aggregate ~8x the bandwidth
    n_bytes, dt = int(5e12), 1.0  # 5 TB/s implied
    assert bench._implausible(n_bytes, dt, 1) is not None
    assert bench._implausible(n_bytes, dt, 8) is None


def test_check_total_raises_not_asserts():
    with pytest.raises(RuntimeError, match="expected"):
        bench._check_total(np.asarray([1, 2, 3], np.int32), 100)
    bench._check_total(np.asarray([1, 2, 3], np.int32), 6)  # no raise


def _mini_state(i):
    cfg = SimConfig(n_nodes=3, n_instances=i, proposers=(0,))
    wl = simm.default_workload(cfg)
    pend, gate, tail, c = simm.prepare_queues(cfg, wl)
    return simm.init_state(cfg, pend, gate, tail, prng.root_key(0))


def test_timed_sim_runs_withholds_artifact_record():
    """A lying timer (instant 'run' claiming 20k rounds of work) must
    produce an error record with raw timings, not a throughput value
    — the exact BENCH_r04 failure shape."""
    i = 1 << 18
    st0 = _mini_state(i)

    def instant_go(root, st):
        return st._replace(
            t=jnp.int32(20_000),
            done=jnp.bool_(True),
            met=st.met._replace(
                chosen_vid=jnp.zeros_like(st.met.chosen_vid)
            ),
        )

    rec = bench._timed_sim_runs(
        instant_go, lambda k: jnp.int32(k), st0, i, {"devices": 1}
    )
    assert "error" in rec and "roofline" in rec["error"]
    assert "value" not in rec
    assert len(rec["raw_timings_s"]) == 3


def test_timed_sim_runs_withholds_nonconverged_value():
    """If a timed run resolves less work than the warmup (done=False
    at max_rounds), the record reports timings and counts but no
    n_instances/dt value — which would overstate throughput."""
    i = 1 << 16
    st0 = _mini_state(i)

    def flaky_go(root, st):
        full = root == 3  # warmup seed converges; timed seeds don't
        n = jnp.where(full, i, i // 2)
        cv = jnp.where(jnp.arange(i) < n, 1, -1).astype(jnp.int32)
        return st._replace(
            t=jnp.int32(3),
            done=full,
            met=st.met._replace(chosen_vid=cv),
        )

    rec = bench._timed_sim_runs(
        flaky_go, lambda k: jnp.int32(k), st0, i, {"devices": 1}
    )
    assert "error" in rec and "value" not in rec
    assert rec["chosen_counts"]["warmup"] == i
    assert all(c == i // 2 for c in rec["chosen_counts"]["timed"])


def test_fleet_record_publishes_plausible_rate():
    # 8 lanes of ~1 MiB state over >= 30 rounds in 100 ms: fine
    rec = bench._fleet_record(
        [0.100, 0.110, 0.120], 8 << 20, 30, 8, 1, {"devices": 1}
    )
    assert rec["value"] == pytest.approx(8 / 0.110, abs=0.005)  # 2-dp round
    assert rec["unit"] == "lanes/sec"
    assert len(rec["raw_timings_s"]) == 3


def test_fleet_record_withholds_implausible_rate():
    """A lying fleet timing (1 GiB of lane state x 1000 rounds in a
    microsecond) must produce an error record with raw timings and NO
    value — no roofline-clamped number is ever published."""
    rec = bench._fleet_record(
        [1e-6, 2e-6, 3e-6], 1 << 30, 1000, 64, 1, {"devices": 1}
    )
    assert "error" in rec and "roofline" in rec["error"]
    assert "value" not in rec
    assert rec["raw_timings_s"] == [0.0, 0.0, 0.0]


def test_geo_record_publishes_per_preset_rates():
    # two presets, 8 lanes of ~1 MiB state over >= 30 rounds: fine
    rec = bench._geo_record(
        {"wan-3region": [0.10, 0.11, 0.12],
         "wan-5region": [0.20, 0.21, 0.22]},
        8 << 20, 30, 8, 1, 0, [], {"devices": 1},
    )
    assert rec["value"]["wan-3region"] == pytest.approx(8 / 0.11, abs=0.005)
    assert rec["value"]["wan-5region"] == pytest.approx(8 / 0.21, abs=0.005)
    assert rec["warm_compiles_across_presets"] == 0
    assert rec["unit"] == "lanes/sec"


def test_geo_record_withholds_on_warm_compiles():
    """The record's claim IS the shared executable: any compile after
    the first preset withholds the whole record, plausible timings or
    not."""
    rec = bench._geo_record(
        {"wan-3region": [0.10, 0.11, 0.12],
         "wan-5region": [0.20, 0.21, 0.22]},
        8 << 20, 30, 8, 1, 2, [], {"devices": 1},
    )
    assert "error" in rec and "one-envelope-executable" in rec["error"]
    assert "value" not in rec
    assert rec["raw_timings_s"]["wan-3region"] == [0.10, 0.11, 0.12]


def test_geo_record_withholds_on_parity_failure():
    """A scalar-vs-uniform-matrix or fleet-vs-single-run mismatch is
    a forked fault model — the record is withheld NAMING the
    failure, like _serve_record's p99-mismatch withhold."""
    rec = bench._geo_record(
        {"wan-3region": [0.10, 0.11, 0.12]},
        8 << 20, 30, 8, 1, 0,
        ["scalar knobs != uniform-matrix twin (sha parity)"],
        {"devices": 1},
    )
    assert "error" in rec and "parity withheld" in rec["error"]
    assert "sha parity" in rec["error"]
    assert "value" not in rec


def test_geo_record_withholds_implausible_rate():
    rec = bench._geo_record(
        {"wan-3region": [1e-6, 2e-6, 3e-6]},
        1 << 30, 1000, 64, 1, 0, [], {"devices": 1},
    )
    assert "error" in rec and "roofline" in rec["error"]
    assert "wan-3region" in rec["error"]
    assert "value" not in rec


def test_envelope_record_publishes_padding_toll():
    # two geometries, 8 lanes of ~1 MiB state over >= 30 rounds
    rec = bench._envelope_record(
        {"3-node": {"unpadded": [0.10, 0.11, 0.12],
                    "padded": [0.20, 0.22, 0.24]},
         "5-node": {"unpadded": [0.20, 0.21, 0.22],
                    "padded": [0.30, 0.33, 0.36]}},
        {"3-node": {"unpadded": 4 << 20, "padded": 8 << 20},
         "5-node": {"unpadded": 6 << 20, "padded": 8 << 20}},
        30, 8, 1, 0, 6, [], [], {"devices": 1},
    )
    v = rec["value"]["3-node"]
    assert v["unpadded_lanes_per_sec"] == pytest.approx(8 / 0.11, abs=0.005)
    assert v["padded_lanes_per_sec"] == pytest.approx(8 / 0.22, abs=0.005)
    assert v["padding_toll_pct"] == pytest.approx(100.0, abs=0.5)
    assert rec["executables_before"] == 6
    assert rec["executables_after"] == 1
    assert rec["warm_compiles_in_sweep"] == 0


def test_envelope_record_withholds_on_warm_compiles():
    """The record's claim IS the one shared padded executable: any
    compile after the first dispatch of the grid withholds the whole
    record, plausible timings or not."""
    rec = bench._envelope_record(
        {"3-node": {"unpadded": [0.10, 0.11, 0.12],
                    "padded": [0.20, 0.22, 0.24]}},
        {"3-node": {"unpadded": 4 << 20, "padded": 8 << 20}},
        30, 8, 1, 3, 6, [], [], {"devices": 1},
    )
    assert "error" in rec and "one-padded-executable" in rec["error"]
    assert "value" not in rec


def test_envelope_record_withholds_on_parity_failure():
    """A padded-vs-bound-free decision-log mismatch means padding
    forked the model — withheld naming the lane."""
    rec = bench._envelope_record(
        {"3-node": {"unpadded": [0.10], "padded": [0.20]}},
        {"3-node": {"unpadded": 4 << 20, "padded": 8 << 20}},
        30, 8, 1, 0, 6,
        ["3-node lane 2: padded dispatch != bound-free twin"],
        [], {"devices": 1},
    )
    assert "error" in rec and "parity withheld" in rec["error"]
    assert "lane 2" in rec["error"]
    assert "value" not in rec


def test_envelope_record_withholds_unconverged_lanes():
    """lanes/sec TO VERDICT: a lane that rides out max_rounds makes
    the timing a measurement of the cap — withheld by name."""
    rec = bench._envelope_record(
        {"7-node": {"unpadded": [0.10], "padded": [0.20]}},
        {"7-node": {"unpadded": 4 << 20, "padded": 8 << 20}},
        30, 8, 1, 0, 6, [],
        ["7-node/padded rep 0: 8 lane(s) without a verdict"],
        {"devices": 1},
    )
    assert "error" in rec and "to-verdict withheld" in rec["error"]
    assert "7-node" in rec["error"]
    assert "value" not in rec


def test_envelope_record_withholds_implausible_rate():
    rec = bench._envelope_record(
        {"5-node": {"unpadded": [1e-6, 2e-6, 3e-6],
                    "padded": [0.20, 0.22, 0.24]}},
        {"5-node": {"unpadded": 1 << 30, "padded": 1 << 30}},
        1000, 64, 1, 0, 6, [], [], {"devices": 1},
    )
    assert "error" in rec and "roofline" in rec["error"]
    assert "5-node/unpadded" in rec["error"]
    assert "value" not in rec


def test_serve_record_publishes_plausible_rate():
    # ~1 MiB of loop state over >= 100 rounds in ~0.5 s: fine
    pts = [{"rate_milli": 4000, "p99": 30, "sustained": True}]
    knee = {"last_sustained_milli": 4000, "first_saturated_milli": None}
    rec = bench._serve_record(
        [0.50, 0.52, 0.55], [0.90, 0.95, 1.00], 1 << 20, 100, 4096,
        pts, knee, 97, 97, {"devices": 1},
    )
    assert rec["value"] == pytest.approx(4096 / 0.52, abs=0.1)
    assert rec["unit"] == "values/sec"
    assert rec["overlap"]["speedup"] == pytest.approx(0.95 / 0.52, abs=0.01)
    assert rec["overlap"]["p99_rounds"] == 97
    assert rec["latency_at_load"] == pts and rec["knee"] == knee


def test_serve_record_withholds_implausible_rate():
    """A lying serve timing (1 GiB of loop state x 1000 rounds in a
    microsecond) must produce an error record with raw timings and NO
    value — no roofline-clamped number is ever published, on either
    dispatch mode's timing set."""
    for pipe, seq in (
        ([1e-6, 2e-6, 3e-6], [0.9, 0.95, 1.0]),  # pipelined lies
        ([0.9, 0.95, 1.0], [1e-6, 2e-6, 3e-6]),  # sequential lies
    ):
        rec = bench._serve_record(
            pipe, seq, 1 << 30, 1000, 4096, [], {}, 97, 97,
            {"devices": 1},
        )
        assert "error" in rec and "roofline" in rec["error"]
        assert "value" not in rec and "overlap" not in rec
        assert len(rec["raw_timings_s"]) == 3
        assert len(rec["sequential_raw_s"]) == 3


def test_serve_record_withholds_on_p99_mismatch():
    """The overlap claim is only meaningful at equal latency; the two
    modes run bit-identical trajectories by construction, so a p99
    mismatch means the harness broke — the record is withheld, never
    published with asterisks."""
    rec = bench._serve_record(
        [0.5, 0.52, 0.55], [0.9, 0.95, 1.0], 1 << 20, 100, 4096,
        [], {}, 97, 115, {"devices": 1},
    )
    assert "error" in rec and "p99 mismatch" in rec["error"]
    assert "value" not in rec


def _fleet_cells():
    return [
        {"lanes": 1, "rate_milli": 4000, "wall_s": 0.50, "rounds": 200,
         "decided": 256, "state_bytes": 1 << 20, "sustained": True},
        {"lanes": 8, "rate_milli": 4000, "wall_s": 0.60, "rounds": 200,
         "decided": 2048, "state_bytes": 1 << 20, "sustained": True},
    ]


def test_serve_fleet_record_publishes_surface():
    knee = [{"lanes": 1, "last_sustained_milli": 4000,
             "first_saturated_milli": None}]
    rec = bench._serve_fleet_record(
        _fleet_cells(), knee, 0, [], {"devices": 1}
    )
    assert rec["metric"] == "serve_fleet_sustained_values_per_sec_surface"
    assert rec["value"]["1"]["4000"] == pytest.approx(256 / 0.50, abs=0.1)
    assert rec["value"]["8"]["4000"] == pytest.approx(2048 / 0.60, abs=0.1)
    assert rec["knee_surface"] == knee
    assert rec["warm_compiles_across_grid"] == 0


def test_serve_fleet_record_withholds_on_warm_compiles():
    """The surface's claim IS the shared envelope executable: any
    compile during the measured grid withholds the whole record,
    plausible timings or not — the _geo_record discipline."""
    rec = bench._serve_fleet_record(
        _fleet_cells(), [], 2, [], {"devices": 1}
    )
    assert "error" in rec and "one-envelope-executable" in rec["error"]
    assert "value" not in rec
    assert rec["cells"][0]["lanes"] == 1  # raw cells kept


def test_serve_fleet_record_withholds_on_parity_failure():
    """A 1-lane zero-load fleet run diverging from closed-loop run()
    means the lane program forked the protocol — the record is
    withheld NAMING the failure, never published with asterisks."""
    rec = bench._serve_fleet_record(
        _fleet_cells(), [], 0,
        ["1-lane zero-load fleet serve != closed-loop run() (sha256)"],
        {"devices": 1},
    )
    assert "error" in rec and "zero-load parity" in rec["error"]
    assert "sha256" in rec["error"]
    assert "value" not in rec


def test_serve_fleet_record_withholds_implausible_cell():
    """A lying cell timing (64 lanes x 1 GiB of loop state x 1000
    rounds in a microsecond) withholds the record naming the
    (lanes, rate) cell — no roofline-clamped surface entry is ever
    published."""
    cells = _fleet_cells() + [{
        "lanes": 64, "rate_milli": 128_000, "wall_s": 1e-6,
        "rounds": 1000, "decided": 4096, "state_bytes": 1 << 30,
        "sustained": False,
    }]
    rec = bench._serve_fleet_record(cells, [], 0, [], {"devices": 1})
    assert "error" in rec and "roofline" in rec["error"]
    assert "lanes=64" in rec["error"] and "128000" in rec["error"]
    assert "value" not in rec


def test_member_record_publishes_with_parity_and_host_block():
    """The membership host-vs-device record: per-seed sha parity and
    plausible timings publish the DEVICE rate (slowest run) with the
    host-stepped figure and speedup alongside."""
    host = [(2.0, 30, "aa"), (2.1, 30, "bb")]
    dev = [(1.0, 30, "aa"), (0.9, 30, "bb")]
    rec = bench._member_record(host, dev, 1 << 20, {"devices": 1})
    assert rec["metric"] == "member_rounds_per_sec"
    assert rec["value"] == pytest.approx(30 / 1.0, abs=0.1)
    assert rec["host_stepped"]["member_rounds_per_sec"] == pytest.approx(
        30 / 2.1, abs=0.1
    )
    assert rec["host_stepped"]["speedup"] == pytest.approx(
        (30 / 1.0) / (30 / 2.1), abs=0.01
    )
    assert rec["parity"]["decision_log_sha256"] == "aa"


def test_member_record_withholds_on_sha_mismatch():
    """A decision-log divergence between the host-stepped and
    device-resident drivers means the ChurnTable interpreters split —
    the speedup claim is withheld, never published with asterisks."""
    host = [(2.0, 30, "aa"), (2.1, 30, "bb")]
    dev = [(1.0, 30, "aa"), (0.9, 30, "XX")]
    rec = bench._member_record(host, dev, 1 << 20, {"devices": 1})
    assert "error" in rec and "sha256 mismatch" in rec["error"]
    assert "run 1" in rec["error"]
    assert "value" not in rec and "host_stepped" not in rec
    assert rec["raw_timings_s"] and rec["host_raw_s"]


def test_member_record_withholds_implausible_timing():
    """A lying timing on EITHER driver (1 GiB of state x 30 rounds in
    a microsecond) withholds the record — the roofline guard applies
    to the baseline side too, or the speedup could be inflated by an
    artificially slow host figure's plausible-looking twin."""
    for host, dev in (
        ([(1e-6, 30, "aa")], [(1.0, 30, "aa")]),
        ([(2.0, 30, "aa")], [(1e-6, 30, "aa")]),
    ):
        rec = bench._member_record(host, dev, 1 << 30, {"devices": 1})
        assert "error" in rec and "roofline" in rec["error"]
        assert "value" not in rec


def test_guard_headline_publishes_measured_rate():
    # 1 GiB state, 10 ms median: plausible — median rate published
    rate, upper, note = bench._guard_headline(
        [0.010, 0.011, 0.012], 1 << 30, 1, 1000
    )
    assert rate == pytest.approx(1000 / 0.011)
    assert upper is None and note is None


def test_guard_headline_falls_back_to_slowest():
    # median implausible, slowest fine: slowest-timing rate, noted
    rate, upper, note = bench._guard_headline(
        [1e-6, 1e-6, 0.010], 1 << 30, 1, 1000
    )
    assert rate == pytest.approx(1000 / 0.010)
    assert upper is None and "slowest" in note


def test_guard_headline_withholds_when_all_implausible():
    """ADVICE round 5: a roofline-synthesized number must never be
    published as `value` — it moves to value_upper_bound and the value
    is withheld."""
    rate, upper, note = bench._guard_headline(
        [1e-6, 2e-6, 3e-6], 1 << 30, 1, 1000
    )
    assert rate is None
    assert upper == pytest.approx(
        1000 / ((1 << 30) / bench.ROOFLINE_BYTES_PER_SEC)
    )
    assert "withheld" in note and "value_upper_bound" in note
