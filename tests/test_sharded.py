"""Instance-axis sharding on the 8-device virtual CPU mesh.

Validates that the shard_map'd round matches the single-chip fast path
bit-for-bit and keeps the invariants — the multi-chip story the driver
dry-runs (BASELINE config 4 shape, scaled down).
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import fast
from tpu_paxos.harness import validate
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel import sharded


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8


def test_sharded_matches_single_chip():
    n_inst, n_nodes, quorum = 1024, 7, 4
    m = pmesh.make_instance_mesh()
    vids = jnp.arange(n_inst, dtype=jnp.int32)

    ref_state, ref_n = fast.choose_all(
        fast.init_state(n_inst, n_nodes), vids, proposer=0, quorum=quorum
    )

    state = sharded.init_sharded_state(m, n_inst, n_nodes)
    fn = sharded.sharded_choose_all(m, proposer=0, quorum=quorum)
    state, n = fn(state, pmesh.shard_instances(m, vids))

    assert int(n) == int(ref_n) == n_inst
    np.testing.assert_array_equal(
        np.asarray(state.learned), np.asarray(ref_state.learned)
    )
    np.testing.assert_array_equal(
        np.asarray(state.promised), np.asarray(ref_state.promised)
    )
    validate.check_all(fast.learned_ia(state), np.arange(n_inst))


def test_sharded_respects_preaccepted_across_shards():
    # A pre-accepted value on a shard-local instance must survive a
    # new proposer running over the whole sharded log.
    n_inst, n_nodes, quorum = 64, 3, 2
    m = pmesh.make_instance_mesh()
    state = sharded.init_sharded_state(m, n_inst, n_nodes)
    # Pre-accept vid 999 at instance 40 (lives on shard 5) at ballot (3,1).
    acc_ballot = np.asarray(state.acc_ballot).copy()
    acc_vid = np.asarray(state.acc_vid).copy()
    from tpu_paxos.core import ballot as bal

    acc_ballot[1, 40] = int(bal.make(3, 1))  # [node, inst] layout
    acc_vid[1, 40] = 999
    # Seed max_seen so the new proposer must out-ballot (3,1).
    max_seen = np.asarray(state.max_seen).copy()
    max_seen[:] = int(bal.make(3, 1))
    from jax.sharding import NamedSharding, PartitionSpec as P  # paxlint: allow[SH001] test pre-places a corrupted state by hand

    minor_i = NamedSharding(m, P(None, pmesh.INSTANCE_AXIS))
    state = fast.FastState(
        promised=state.promised,
        max_seen=jnp.asarray(max_seen),  # [A]: replicated
        acc_ballot=jax.device_put(jnp.asarray(acc_ballot), minor_i),
        acc_vid=jax.device_put(jnp.asarray(acc_vid), minor_i),
        learned=state.learned,
    )
    vids = jnp.arange(n_inst, dtype=jnp.int32)
    fn = sharded.sharded_choose_all(m, proposer=0, quorum=quorum)
    state, n = fn(state, pmesh.shard_instances(m, vids))
    assert int(n) == n_inst
    learned = fast.learned_ia(state)  # [I, A]
    assert (learned[40] == 999).all()
    validate.check_agreement(learned)


def test_uneven_shard_rejected():
    m = pmesh.make_instance_mesh()
    try:
        sharded.init_sharded_state(m, 100, 3)  # 100 % 8 != 0
    except ValueError:
        pass
    else:
        raise AssertionError("uneven instance count not rejected")
