"""Shared subprocess-environment builder for CLI/e2e tests.

Every test that shells out (paxlint CLI, jaxpr-audit CLI, the census
e2e run) needs the same scrub: drop the host's JAX_/XLA_ selection
(the subprocess picks its own platform) plus any test-specific knobs,
and rebuild PYTHONPATH through ``__graft_entry__.scrub_pythonpath``
so the repo under test wins over any injected site dirs.  One helper,
three call sites — an env-handling fix lands once.
"""

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scrubbed_env(extra_prefixes=(), **overrides) -> dict:
    """Copy of os.environ minus JAX_/XLA_/``extra_prefixes`` keys,
    with a scrubbed repo-first PYTHONPATH; ``overrides`` are applied
    last."""
    drop = ("JAX_", "XLA_") + tuple(extra_prefixes)
    env = {
        k: v for k, v in sorted(os.environ.items())
        if not k.startswith(drop)
    }
    import __graft_entry__ as ge

    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + ge.scrub_pythonpath(env.get("PYTHONPATH", ""))
    )
    env.update(overrides)
    return env
