"""Repro-artifact schema validation (analysis/artifact_schema.py):
well-formed artifacts pass, every class of corruption fails with an
error naming the offending field, and the check is wired into
``load_artifact`` (the ``python -m tpu_paxos repro`` load path)."""

import copy
import json
import os

import pytest

from tpu_paxos.analysis.artifact_schema import (
    ARTIFACT_FORMAT,
    ArtifactSchemaError,
    validate_artifact,
)


def valid_artifact() -> dict:
    """Structurally identical to harness/shrink.save_artifact output
    (tests/test_shrink.py covers the real producer end-to-end; this
    literal keeps the schema tests engine-free and fast)."""
    return {
        "format": ARTIFACT_FORMAT,
        "cfg": {
            "n_nodes": 3,
            "n_instances": 16,
            "proposers": [0, 1],
            "seed": 7,
            "max_rounds": 500,
            "assign_window": 64,
            "protocol": {
                "prepare_delay_min": 0,
                "prepare_delay_max": 4,
                "prepare_retry_count": 3,
                "prepare_retry_timeout": 2,
                "accept_retry_count": 3,
                "accept_retry_timeout": 2,
                "commit_retry_timeout": 2,
            },
            "faults": {
                "drop_rate": 500,
                "dup_rate": 0,
                "min_delay": 0,
                "max_delay": 2,
                "crash_rate": 0,
                "schedule": {
                    "episodes": [
                        {
                            "kind": "partition",
                            "t0": 4,
                            "t1": 9,
                            "groups": [[0], [1, 2]],
                            "src": [],
                            "dst": [],
                            "nodes": [],
                            "drop_rate": 0,
                        },
                        {
                            "kind": "burst",
                            "t0": 0,
                            "t1": 3,
                            "groups": [],
                            "src": [],
                            "dst": [],
                            "nodes": [],
                            "drop_rate": 2500,
                        },
                    ]
                },
            },
        },
        "workload": [[100, 101], [200]],
        "gates": None,
        "chains": [[100, 101]],
        "extra_checks": {"decision_round_max": 40},
        "violation": "no quiescence in 500 rounds",
        "decision_log_sha256": "ab" * 32,
        "rounds": 500,
    }


def _expect_field(art, field):
    with pytest.raises(ArtifactSchemaError) as ei:
        validate_artifact(art)
    assert ei.value.field == field, (
        f"expected error at {field!r}, got {ei.value.field!r}: {ei.value}"
    )


def test_valid_artifact_passes():
    validate_artifact(valid_artifact())


def test_schedule_null_ok():
    art = valid_artifact()
    art["cfg"]["faults"]["schedule"] = None
    validate_artifact(art)


def test_missing_required_field_named():
    art = valid_artifact()
    del art["decision_log_sha256"]
    _expect_field(art, "decision_log_sha256")


def test_wrong_type_named():
    art = valid_artifact()
    art["cfg"]["seed"] = "seven"
    _expect_field(art, "cfg.seed")


def test_bool_is_not_int():
    art = valid_artifact()
    art["cfg"]["n_nodes"] = True
    _expect_field(art, "cfg.n_nodes")


def test_negative_rate_named():
    art = valid_artifact()
    art["cfg"]["faults"]["drop_rate"] = -3
    _expect_field(art, "cfg.faults.drop_rate")


def test_nested_episode_field_named():
    art = valid_artifact()
    art["cfg"]["faults"]["schedule"]["episodes"][1]["kind"] = "meteor"
    _expect_field(art, "cfg.faults.schedule.episodes[1].kind")


def test_workload_element_named():
    art = valid_artifact()
    art["workload"][1] = [200, "two-oh-one"]
    _expect_field(art, "workload[1][1]")


def test_unknown_key_in_closed_struct_named():
    # a hand-edit typo ('node' for 'nodes') must be named by the
    # schema, not die later as Episode's bare ValueError
    art = valid_artifact()
    ep = art["cfg"]["faults"]["schedule"]["episodes"][0]
    ep["node"] = ep.pop("nodes")
    _expect_field(art, "cfg.faults.schedule.episodes[0].node")


def test_unknown_key_under_faults_named():
    art = valid_artifact()
    art["cfg"]["faults"]["drop_rte"] = 5
    _expect_field(art, "cfg.faults.drop_rte")


def test_extra_checks_stays_open():
    art = valid_artifact()
    art["extra_checks"]["some_future_check"] = {"x": 1}
    validate_artifact(art)


def test_bad_sha256_named():
    art = valid_artifact()
    art["decision_log_sha256"] = "nothex"
    _expect_field(art, "decision_log_sha256")


def test_wrong_format_const():
    art = valid_artifact()
    art["format"] = "tpu-paxos-repro-99"
    _expect_field(art, "format")


def test_wrong_format_reaches_clean_cli_surface(tmp_path):
    """A wrong/missing format flows through the schema (not a bare
    ValueError), so load_artifact callers get the field-named error
    and ``repro`` its clean exit 2."""
    from tpu_paxos.harness import shrink

    for mutate in (lambda a: a.__setitem__("format", "tpu-paxos-repro-0"),
                   lambda a: a.pop("format")):
        art = valid_artifact()
        mutate(art)
        path = tmp_path / "fmt.json"
        path.write_text(json.dumps(art))
        with pytest.raises(ArtifactSchemaError) as ei:
            shrink.load_artifact(str(path))
        assert ei.value.field == "format"


def test_cross_field_proposer_range():
    art = valid_artifact()
    art["cfg"]["proposers"] = [0, 5]
    _expect_field(art, "cfg.proposers[1]")


def test_cross_field_workload_arity():
    art = valid_artifact()
    art["workload"] = [[1]]
    _expect_field(art, "workload")


def test_cross_field_gates_arity():
    art = valid_artifact()
    art["gates"] = [[-1, -1]]
    _expect_field(art, "gates")


def test_load_artifact_applies_schema(tmp_path):
    """The repro load path rejects a corrupt artifact with the field
    name AND the file path in the message (the user-facing surface)."""
    from tpu_paxos.harness import shrink

    art = valid_artifact()
    art["cfg"]["faults"]["schedule"]["episodes"][0]["t0"] = -4
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(art))
    with pytest.raises(ArtifactSchemaError) as ei:
        shrink.load_artifact(str(path))
    assert ei.value.field == "cfg.faults.schedule.episodes[0].t0"
    assert "bad.json" in str(ei.value)


def test_load_artifact_truncated_json_clean_error(tmp_path):
    """A truncated artifact (killed stress run) surfaces as
    ArtifactSchemaError — reaching repro's exit-2 path — not a raw
    JSONDecodeError traceback."""
    from tpu_paxos.harness import shrink

    path = tmp_path / "trunc.json"
    path.write_text(json.dumps(valid_artifact())[:57])
    with pytest.raises(ArtifactSchemaError, match="invalid JSON"):
        shrink.load_artifact(str(path))
    with pytest.raises(ArtifactSchemaError, match="unreadable"):
        shrink.load_artifact(str(tmp_path / "nonexistent.json"))


def test_load_artifact_semantic_constraint_clean_error(tmp_path):
    """Constraints enforced by the config/episode constructors beyond
    the schema's type/range checks (here: an empty episode interval)
    still surface as ArtifactSchemaError, not a raw ValueError."""
    from tpu_paxos.harness import shrink

    art = valid_artifact()
    art["cfg"]["faults"]["schedule"]["episodes"][0]["t1"] = 4
    art["cfg"]["faults"]["schedule"]["episodes"][0]["t0"] = 4
    path = tmp_path / "empty_interval.json"
    path.write_text(json.dumps(art))
    with pytest.raises(ArtifactSchemaError, match="config validation"):
        shrink.load_artifact(str(path))


def test_load_artifact_accepts_valid(tmp_path):
    from tpu_paxos.harness import shrink

    path = tmp_path / "ok.json"
    path.write_text(json.dumps(valid_artifact()))
    case, art = shrink.load_artifact(str(path))
    assert case.cfg.n_nodes == 3
    assert art["format"] == ARTIFACT_FORMAT
    # shrink re-exports the constant from the schema module
    assert shrink.ARTIFACT_FORMAT == ARTIFACT_FORMAT


def test_repro_cli_exit_code_on_schema_error(tmp_path, monkeypatch):
    """``python -m tpu_paxos repro <bad>`` exits 2 with a JSON
    summary naming the field (in-process: backend=auto is a no-op)."""
    from tpu_paxos import __main__ as cli

    # pre-set the flag so run_repro's setdefault leaves it alone and
    # monkeypatch teardown restores the ORIGINAL state (a trailing
    # delenv would record run_repro's "1" and re-set it session-wide)
    monkeypatch.setenv("TPU_PAXOS_DETERMINISTIC", "0")
    art = valid_artifact()
    art["rounds"] = -1
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(art))
    rc = cli.run_repro([str(path), "--json"])
    assert rc == 2


def test_error_message_shape():
    art = valid_artifact()
    art["cfg"]["protocol"]["prepare_delay_max"] = None
    try:
        validate_artifact(art)
    except ArtifactSchemaError as e:
        assert "cfg.protocol.prepare_delay_max" in str(e)
        assert "null" in e.problem
    else:
        raise AssertionError("expected ArtifactSchemaError")


def test_deep_copy_safety():
    # validation must not mutate the artifact it inspects
    art = valid_artifact()
    snapshot = copy.deepcopy(art)
    validate_artifact(art)
    assert art == snapshot


def test_telemetry_never_leaks_into_artifacts():
    """The flight recorder is recomputed at replay (``python -m
    tpu_paxos trace``), NEVER stored: the artifact format stamp and
    the declared schema key set are pinned at their pre-telemetry
    values, and the committed fleet-quick wedge artifact — the real
    producer's output — carries no keys outside the declared set."""
    from tpu_paxos.analysis.artifact_schema import ARTIFACT_SCHEMA

    assert ARTIFACT_FORMAT == "tpu-paxos-repro-1"
    assert set(ARTIFACT_SCHEMA.props) == {
        "format", "engine", "devices", "cfg", "workload", "gates",
        "chains", "extra_checks", "violation", "decision_log_sha256",
        "rounds", "serve",
        # "serve" (PR 16) is REPLAY INPUT — arrivals, priorities, the
        # control policy, and the decision trail — not telemetry; the
        # recorder's output stays recomputed at replay
    }, "artifact schema grew a field — telemetry must stay recomputed"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wedge = os.path.join(repo, "stress-triage",
                         "repro_fleet_g0_lane0.json")
    art = json.load(open(wedge))
    assert set(art) <= set(ARTIFACT_SCHEMA.props), sorted(
        set(art) - set(ARTIFACT_SCHEMA.props)
    )
