"""ChurnTable + device-resident membership driver (PR 12).

The contracts, in dependency order: the churn-schedule data model
round-trips and validates; the compiled-constant and runtime-table
builds of the membership round are STATE-IDENTICAL per round (the
ScheduleTable parity discipline, crash masks included); the
host-stepped and device-resident drivers of the same ChurnTable are
decision-log sha256-IDENTICAL on a churn+crash+pause mix; the device
scenario itself converges with prefix-consistent logs; and the
deterministic ``crash`` episode kind — which PR 8 made this engine
reject — now fail-stops exactly like the host ``crash()`` injector.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.core import faults as flt
from tpu_paxos.core import values as val
from tpu_paxos.fleet import schedule_table as stm
from tpu_paxos.harness import validate
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.membership import engine as meng
from tpu_paxos.utils import prng


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


# ---------------- data model ----------------

def test_churn_event_validation():
    with pytest.raises(ValueError, match="vid"):
        ctm.ChurnEvent(vid=-1)
    with pytest.raises(ValueError, match="wait"):
        ctm.ChurnEvent(vid=1, wait=7)
    with pytest.raises(ValueError, match="t0"):
        ctm.ChurnEvent(vid=1, t0=-2)
    with pytest.raises(ValueError, match="first event"):
        ctm.ChurnSchedule((ctm.ChurnEvent(vid=1, wait=ctm.WAIT_CHOSEN),))
    with pytest.raises(ValueError, match="distinct"):
        ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=1),
            ctm.ChurnEvent(vid=1, wait=ctm.WAIT_CHOSEN),
        ))


def test_churn_schedule_json_roundtrip():
    sched = ctm.grow_shrink_schedule(4, 2, values_per_step=2)
    again = ctm.ChurnSchedule.from_dict(sched.to_dict())
    assert again == sched
    assert ctm.ChurnSchedule.from_dict({"events": []}) == ctm.ChurnSchedule()


def test_encode_churn_padding_and_bounds():
    sched = ctm.ChurnSchedule((
        ctm.ChurnEvent(vid=5, via=1, t0=3),
        ctm.ChurnEvent(vid=6, wait=ctm.WAIT_APPLIED),
    ))
    tab = ctm.encode_churn(sched, 3, max_events=4)
    assert tab.vid.tolist() == [5, 6, int(val.NONE), int(val.NONE)]
    assert tab.via.tolist() == [1, 0, 0, 0]
    assert int(tab.n_events) == 2
    assert not tab.is_change.any()
    with pytest.raises(ValueError, match="capacity"):
        ctm.encode_churn(sched, 3, max_events=1)
    with pytest.raises(ValueError, match="via node"):
        ctm.encode_churn(
            ctm.ChurnSchedule((ctm.ChurnEvent(vid=1, via=9),)), 3
        )
    with pytest.raises(ValueError, match="changes node"):
        ctm.encode_churn(
            ctm.ChurnSchedule((
                ctm.ChurnEvent(vid=meng.change_vid(5, meng.ADD_ACCEPTOR)),
            )),
            3,
        )


def test_encode_churn_batch_stacks_lanes():
    a = ctm.ChurnSchedule((ctm.ChurnEvent(vid=1),))
    b = ctm.grow_shrink_schedule(3, 2)
    tabs = ctm.encode_churn_batch([a, b, None], 3)
    assert tabs.vid.shape == (3, len(b.events))
    assert tabs.n_events.tolist() == [1, len(b.events), 0]
    assert tabs.is_change[1].any()  # lane b carries change vids


def test_grow_shrink_schedule_shape():
    sched = ctm.grow_shrink_schedule(7, 5, values_per_step=1)
    # 6 values + 6 adds + 2 dels, change vids marked, dels wait Applied
    assert len(sched.events) == 14
    kinds = [e.vid >= meng.CHANGE_BASE for e in sched.events]
    assert sum(kinds) == 8
    assert sched.events[-1].wait == ctm.WAIT_APPLIED


# ---------------- compile-const vs runtime-table parity ----------------

def _active_init(n, i, c):
    """Initial state with queued work so the parity steps exercise the
    accept/apply/learn blocks, not just quiet rounds."""
    st = meng._init(n, i, c)
    vids = [100, meng.change_vid(1, meng.ADD_ACCEPTOR), 101]
    pend = st.pend
    for k, v in enumerate(vids):
        pend = pend.at[0, k].set(v)
    return st._replace(pend=pend, tail=st.tail.at[0].set(len(vids)))


def test_const_vs_runtime_round_parity_per_round():
    """The tentpole's mask-parity pin: the compiled-constant and
    runtime-ScheduleTable builds of the membership round produce
    IDENTICAL states round for round, on a schedule mixing a
    partition, a pause, and a deterministic crash point (so the
    crash-row read parity is covered too)."""
    n, i = 4, 16
    c = i * 2 + 8
    sched = flt.FaultSchedule((
        flt.partition(2, 6, (0, 1), (2, 3)),
        flt.pause(4, 9, 2),
        flt.crash(7, 3),
    ))
    rf_c = jax.jit(meng._build_round(
        n, i, c, crash_rate=500, comp=flt.compile_schedule(sched, n),
    ))
    rf_r = jax.jit(meng._build_round(
        n, i, c, crash_rate=500, runtime_schedule=True,
    ))
    tab = jax.tree.map(jnp.asarray, stm.encode_schedule(sched, n, 5))
    root = prng.root_key(3)
    st_c = st_r = _active_init(n, i, c)
    for t in range(sched.horizon + 4):
        st_c = rf_c(root, st_c)
        st_r = rf_r(root, st_r, tab)
        for name, a, b in zip(
            st_c._fields, jax.tree.leaves(st_c), jax.tree.leaves(st_r)
        ):
            assert (np.asarray(a) == np.asarray(b)).all(), (t, name)
    # the crash point actually fired on both paths
    assert bool(np.asarray(st_c.crashed)[3])


# ---------------- host-stepped vs device-resident drivers ----------------

def test_host_vs_device_decision_log_sha256_parity():
    """THE tentpole contract: the same ChurnTable through the legacy
    host-stepped loop (per-round host reads) and through the
    device-resident while_loop is decision-log sha256-identical, on a
    churn + crash + pause mix — and so is the runtime-table twin of
    the same engine."""
    churn = ctm.grow_shrink_schedule(4, 2, values_per_step=1)
    sched = flt.FaultSchedule((
        flt.pause(5, 11, 2),
        flt.crash(18, 3),
    ))
    eng = meng.ChurnEngine(
        4, 24, churn=churn, schedule=sched, crash_rate=500,
        max_rounds=400,
    )
    dev = eng.run(seed=2)
    host = eng.run_host(seed=2)
    assert dev.done and host.done
    assert dev.rounds == host.rounds
    assert _sha(dev.decision_log()) == _sha(host.decision_log())

    rt = meng.ChurnEngine(
        4, 24, runtime_tables=True, max_events=16, max_episodes=4,
        crash_rate=500, max_rounds=400,
    )
    r2 = rt.run(seed=2, churn=churn, schedule=sched)
    assert _sha(r2.decision_log()) == _sha(dev.decision_log())


def test_device_churn_scenario_converges_prefix_consistent():
    """The device driver completes the grow/shrink scenario with
    every value chosen exactly once and prefix-consistent applied
    logs — the invariants the host-driven config-5 test pins, now on
    the one-dispatch path."""
    churn = ctm.grow_shrink_schedule(5, 3, values_per_step=1)
    eng = meng.ChurnEngine(5, 32, churn=churn, max_rounds=600)
    res = eng.run(seed=0)
    assert res.done and res.injected == len(churn.events)
    logs = [meng.applied_log_of(res.state, a) for a in range(5)]
    validate.check_prefix_consistency(logs)
    plain = sorted(
        e.vid for e in churn.events if e.vid < meng.CHANGE_BASE
    )
    assert sorted(logs[0].tolist()) == plain
    counts = np.unique(logs[0], return_counts=True)[1]
    assert (counts == 1).all()


def test_churn_engine_validation_surfaces():
    churn = ctm.ChurnSchedule((ctm.ChurnEvent(vid=1),))
    with pytest.raises(ValueError, match="per run"):
        meng.ChurnEngine(3, 16, churn=churn, runtime_tables=True)
    eng = meng.ChurnEngine(3, 16, churn=churn)
    with pytest.raises(ValueError, match="baked its tables"):
        eng.run(seed=0, churn=churn)
    rt = meng.ChurnEngine(3, 16, runtime_tables=True, max_events=2)
    with pytest.raises(ValueError, match="node 0"):
        rt.run(seed=0, churn=churn,
               schedule=flt.FaultSchedule((flt.crash(2, 0),)))
    # pending-ring capacity guard: one node cannot take more events
    # than the ring's requeue-headroom leaves
    i = 4
    too_many = ctm.ChurnSchedule(tuple(
        ctm.ChurnEvent(vid=100 + k) for k in range(2 * i + 8 - i + 1)
    ))
    with pytest.raises(ValueError, match="pending ring"):
        meng.ChurnEngine(3, i, churn=too_many, max_rounds=50)


# ---------------- deterministic crash episodes (PR-8 reversal) ----------

def test_member_crash_episode_fail_stops_like_host_crash():
    """A scheduled ``crash(t0, node)`` on the host-stepped engine:
    silent from round t0+1 (the host ``crash()`` timing), epoch
    recorded for the rejoin guard, quorum denominators unchanged."""
    sched = flt.FaultSchedule((flt.crash(6, 2),))
    ms = meng.MemberSim(3, n_instances=24, seed=0, schedule=sched)
    a = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(a), max_rounds=200)
    b = ms.add_acceptor(2)
    assert ms.run_until(lambda: ms.applied(b), max_rounds=200)
    ms.run_rounds(max(0, 8 - int(ms.state.t)))
    assert 2 in ms.crashed_set()
    assert 2 in ms._crash_round  # rejoin epoch guard observed it
    # the crashed acceptor still counts in the quorum denominator
    assert ms.acceptor_set(0) == {0, 1, 2}
    # survivors keep choosing through the 2-of-3 live majority
    ms.propose(0, 55)
    assert ms.run_until(lambda: ms.chosen(55), max_rounds=400)
