"""Breach attribution (telemetry/diagnose.py): TP/TN fixtures per
cause on crafted windowed series, the ambiguous gray+saturation
window (both candidates ranked, never silently one), determinism
(byte-identical output for identical input), and the seeded-cause
recall pins — a wan-3region gray schedule classifies ``gray-region``,
an over-knee serve rate classifies ``saturation``, a region-pair cut
schedule classifies ``partition`` (slow tier; its fast coverage is
the crafted partition fixture here plus the gray/saturation engine
runs, which exercise the same harvested-series plumbing)."""

import dataclasses
import json

import numpy as np
import pytest

from tpu_paxos.telemetry import diagnose as diag
from tpu_paxos.telemetry import recorder as telem

W = telem.NUM_WINDOWS
B = telem.NUM_LAT_BUCKETS
NP_ = telem.NUM_PHASES
A = 3


def _mk_dict(**over):
    """A quiet, healthy windowed dict (4 active windows of modest
    traffic) the fixtures perturb per cause."""
    d = {
        "window_rounds": 16,
        "n_windows": W,
        "decided": [8] * 4 + [0] * (W - 4),
        "offered": [100] * 4 + [0] * (W - 4),
        "dropped": [1] * 4 + [0] * (W - 4),
        "drop_rate_observed": [100.0] * 4 + [0.0] * (W - 4),
        "stall_max": [0] * W,
        "takeovers": [0] * W,
        "restarts": [0] * W,
        "cut": [0] * W,
        "backlog_max": [1] * 4 + [0] * (W - 4),
        "node_offered": [[30] * A] * 4 + [[0] * A] * (W - 4),
        "node_delay": [[15] * A] * 4 + [[0] * A] * (W - 4),
        "latency_p50": [2] * 4 + [-1] * (W - 4),
        "phase_hist": np.zeros((W, NP_, B), np.int64),
        "lat_hist": np.zeros((W, B), np.int64).tolist(),
    }
    ph = np.asarray(d["phase_hist"])
    ph[:4, telem.PHASE_CONSENSUS, 1] = 8  # modest consensus latency
    d["phase_hist"] = ph.tolist()
    d.update(over)
    return d


def _set_phase(d, w, phase, bucket, n):
    ph = np.asarray(d["phase_hist"])
    ph[w, phase, bucket] = n
    d["phase_hist"] = ph.tolist()


# ---------------- per-cause TP/TN fixtures ----------------


def test_saturation_tp_and_tn():
    d = _mk_dict()
    d["backlog_max"][2] = 20  # growth vs baseline 1
    _set_phase(d, 2, telem.PHASE_QUEUE, 6, 8)  # queue-wait dominates
    v = diag.diagnose_window(d, 2)
    assert v["cause"] == "saturation"
    ev = v["candidates"][0]["evidence"]
    assert ev["backlog"] == 20 and ev["dominant_phase"] == "queue"
    assert ev["drops_nominal"] is True
    # TN: same phase shape but the backlog stays flat — a slow
    # consensus is not saturation
    d2 = _mk_dict()
    _set_phase(d2, 2, telem.PHASE_QUEUE, 6, 8)
    assert diag.diagnose_window(d2, 2)["cause"] == "unknown"
    # TN: backlog grows but latency is consensus-dominated (a duel,
    # not an overload)
    d3 = _mk_dict()
    d3["backlog_max"][2] = 20
    _set_phase(d3, 2, telem.PHASE_CONSENSUS, 7, 20)
    assert "saturation" not in [
        c["cause"] for c in diag.diagnose_window(d3, 2)["candidates"]
    ]


def test_gray_region_tp_named_and_tn():
    d = _mk_dict()
    # node 2's per-copy mean delay triples; others stay at rest
    nd = np.asarray(d["node_delay"])
    nd[2, 2] = 90  # 90/30 copies = 3000 milli vs baseline 500
    d["node_delay"] = nd.tolist()
    rmap = [0, 1, 2]
    v = diag.diagnose_window(
        d, 2, region_map=rmap, region_names=("us", "eu", "ap")
    )
    assert v["cause"] == "gray-region"
    ev = v["candidates"][0]["evidence"]
    assert ev["nodes"] == [2] and ev["regions"] == ["ap"]
    assert ev["backlog_flat"] is True
    # without a region map the NODE is still named
    v2 = diag.diagnose_window(d, 2)
    assert v2["cause"] == "gray-region"
    assert "regions" not in v2["candidates"][0]["evidence"]
    # TN: the same inflation with severed-edge losses in the window
    # is never gray (a gray node slows, it does not sever — and the
    # cut's traffic-mix shift fakes inflation)
    d_cut = json.loads(json.dumps(d))
    d_cut["cut"][2] = 5
    causes = [
        c["cause"] for c in diag.diagnose_window(d_cut, 2)["candidates"]
    ]
    assert "gray-region" not in causes
    # TN: inflation with a drop spike is a sick link, not gray
    d_drop = json.loads(json.dumps(d))
    d_drop["drop_rate_observed"][2] = 2000.0
    causes = [
        c["cause"]
        for c in diag.diagnose_window(d_drop, 2)["candidates"]
    ]
    assert "gray-region" not in causes


def test_gray_attribution_excludes_coinflated_neighbors():
    """Delays charge both edge endpoints, so a gray node's neighbor
    co-inflates by its traffic share — only the node(s) near the max
    inflation delta are named."""
    d = _mk_dict()
    nd = np.asarray(d["node_delay"])
    nd[2, 2] = 90  # node 2: 3000 milli (delta 2500)
    nd[2, 0] = 36  # node 0: 1200 milli (delta 700 — its share of 2's
    d["node_delay"] = nd.tolist()  # inflated edges, not its own outage)
    v = diag.diagnose_window(d, 2)
    assert v["cause"] == "gray-region"
    assert v["candidates"][0]["evidence"]["nodes"] == [2]


def test_partition_tp_named_pair_and_tn():
    d = _mk_dict()
    d["cut"][1] = 12
    d["stall_max"][1] = 3
    pairs = {
        "n_regions": 3,
        "offered": [[10] * 3] * 3,
        "dropped": [[0] * 3] * 3,
        "drop_rate_observed": [[0.0] * 3] * 3,
        "cut": [[0, 0, 9], [0, 0, 3], [0, 0, 0]],
        "names": ["us", "eu", "ap"],
    }
    v = diag.diagnose_window(
        d, 1, region_pairs=pairs, region_names=("us", "eu", "ap")
    )
    assert v["cause"] == "partition"
    ev = v["candidates"][0]["evidence"]
    assert ev["cut_copies"] == 12
    assert ev["pair"] == "us->ap" and ev["pair_cut_total"] == 9
    # TN: no severed copies, no partition
    assert diag.diagnose_window(_mk_dict(), 1)["cause"] == "unknown"


def test_duel_churn_tp_and_tn():
    d = _mk_dict()
    d["takeovers"][3] = 2
    d["restarts"][3] = 3
    _set_phase(d, 3, telem.PHASE_CONSENSUS, 7, 30)  # duels dominate
    v = diag.diagnose_window(d, 3)
    assert v["cause"] == "duel-churn"
    ev = v["candidates"][0]["evidence"]
    assert ev["takeovers"] == 2 and ev["restarts"] == 3
    assert ev["dominant_phase"] == "consensus"
    # TN: one restart is weather, not churn
    d2 = _mk_dict()
    d2["restarts"][3] = 1
    assert diag.diagnose_window(d2, 3)["cause"] == "unknown"


def test_ambiguous_gray_plus_saturation_reports_both_ranked():
    """A window that is BOTH saturating and gray reports both
    candidates, ranked — never silently one (the controller contract:
    shed on saturation, never on gray)."""
    d = _mk_dict()
    d["backlog_max"][2] = 20
    _set_phase(d, 2, telem.PHASE_QUEUE, 6, 8)
    nd = np.asarray(d["node_delay"])
    nd[2, 2] = 90
    d["node_delay"] = nd.tolist()
    v = diag.diagnose_window(d, 2)
    causes = [c["cause"] for c in v["candidates"]]
    assert "saturation" in causes and "gray-region" in causes
    # ranking is deterministic: saturation carries the drops-nominal
    # support point, gray loses its backlog-flat point to the growth
    assert causes[0] == "saturation"
    scores = [c["score"] for c in v["candidates"]]
    assert scores == sorted(scores, reverse=True)


# ---------------- reducers / report plumbing ----------------


def test_diagnose_breaches_and_attach():
    d = _mk_dict()
    d["backlog_max"][2] = 20
    _set_phase(d, 2, telem.PHASE_QUEUE, 6, 8)
    rep = diag.diagnose_breaches(d, [2, 3])
    assert [v["window"] for v in rep["windows"]] == [2, 3]
    assert rep["windows"][0]["cause"] == "saturation"
    assert rep["causes"] == sorted(rep["causes"])
    # attach: union of global + region breach windows, additive
    verdict = {
        "breach_windows": [2],
        "regions": {"ap": {"breach_windows": [3]}},
    }
    out = diag.attach_diagnosis(verdict, d)
    assert [v["window"] for v in out["diagnosis"]["windows"]] == [2, 3]
    assert "diagnosis" not in diag.attach_diagnosis(
        {"breach_windows": []}, d
    )


def test_label_windows_and_series():
    d = _mk_dict()
    d["cut"][1] = 12
    labels = diag.label_windows(d)
    assert labels[1] == "partition"
    assert labels[0] is None  # healthy active window
    assert labels[8] is None  # quiet window
    rep = diag.diagnose_series(d)
    assert [v["window"] for v in rep["windows"]] == [1]
    assert rep["causes"] == ["partition"]


def test_determinism_byte_identical():
    d = _mk_dict()
    d["backlog_max"][2] = 20
    _set_phase(d, 2, telem.PHASE_QUEUE, 6, 8)
    a = diag.diagnose_breaches(d, [2])
    b = diag.diagnose_breaches(json.loads(json.dumps(d)), [2])
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert diag.fingerprint(a) == diag.fingerprint(b)


def test_region_pair_names():
    assert telem.region_pair_name(("us", "eu", "ap"), 0, 2) == "us->ap"
    assert telem.region_pair_name((), 1, 2) == "r1->r2"
    assert telem.region_prefix_names(("us",), 3) == ["us", "r1", "r2"]


# ---------------- seeded-cause recall (engine runs) ----------------


def _wan3_diag(sched, seed=0):
    """One wan-3region closed-loop run -> its diagnosis series."""
    from tpu_paxos.config import SimConfig
    from tpu_paxos.core import sim, wan as wanm

    preset = wanm.WAN3
    faults = wanm.wan_fault_config(preset, 3, schedule=sched)
    cfg = SimConfig(
        n_nodes=3, n_instances=24, proposers=(0, 1), seed=seed,
        max_rounds=256, faults=faults,
    )
    rmap = wanm.node_regions(preset, 3)
    res, summ, wsum = sim.run_with_telemetry(cfg, region_map=rmap)
    sd = telem.summary_to_dict(
        summ, wsum, telem.WINDOW_ROUNDS, region_names=preset.regions
    )
    return diag.diagnose_series(
        sd["windows"], region_map=rmap, region_names=preset.regions,
        region_pairs=sd["region_pairs"],
    )


def test_seeded_gray_region_recall_and_replay_parity():
    """A wan-3region schedule graying the lone 'ap' node classifies
    ``gray-region`` NAMING ap, and the verdict is byte-identical
    across two replays (the determinism acceptance pin)."""
    from tpu_paxos.core import faults as flt

    sched = flt.FaultSchedule((flt.gray(32, 96, 2, delay=4),))
    rep = _wan3_diag(sched)
    assert "gray-region" in rep["causes"]
    gray = [v for v in rep["windows"] if v["cause"] == "gray-region"]
    assert gray, rep
    ev = gray[0]["candidates"][0]["evidence"]
    assert ev["regions"] == ["ap"] and ev["nodes"] == [2]
    # two replays of the same run: byte-identical diagnosis (the
    # second run hits the jit cache — no second compile)
    rep2 = _wan3_diag(sched)
    assert diag.fingerprint(rep) == diag.fingerprint(rep2)


@pytest.mark.slow
def test_seeded_partition_recall():
    """A region-pair cut schedule classifies ``partition`` with the
    severed pair named (us->ap).  Slow tier: the schedule is a
    compile-time constant, so this cell pays its own engine compile;
    fast coverage is the crafted partition fixture above plus the
    gray cell's identical harvested-series plumbing."""
    from tpu_paxos.core import faults as flt

    sched = flt.FaultSchedule((flt.partition(24, 64, (0, 1), (2,)),))
    rep = _wan3_diag(sched)
    assert "partition" in rep["causes"]
    part = [v for v in rep["windows"] if v["cause"] == "partition"]
    assert part, rep
    ev = part[0]["candidates"][0]["evidence"]
    assert ev["pair"] == "us->ap" and ev["cut_copies"] > 0


def test_seeded_saturation_recall_over_knee_serve():
    """An over-knee serve rate breaches its SLO and the breach report
    names ``saturation`` (queue-wait-dominated, backlog growth) —
    threaded end-to-end through serve_run's verdict."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.serve import arrivals as arrv
    from tpu_paxos.serve import harness as sh

    cfg = SimConfig(
        n_nodes=5, n_instances=128, proposers=(0, 1), seed=0,
        max_rounds=20_000, faults=FaultConfig(),
    )
    vids = np.arange(64, dtype=np.int32)
    rounds = arrv.poisson_rounds(64, 4000, 0)
    streams, arrs = arrv.split_round_robin(vids, rounds, 2)
    rep = sh.serve_run(
        cfg, streams, arrs, slo=sh.ServeSLO(latency_rounds=16)
    )
    assert rep.slo is not None and rep.slo["breach_windows"]
    dg = rep.slo["diagnosis"]
    assert "saturation" in dg["causes"]
    top = dg["windows"][0]
    assert top["cause"] == "saturation"
    ev = top["candidates"][0]["evidence"]
    assert ev["dominant_phase"] == "queue" and ev["backlog"] >= 4
    # the sweep summary carries the causes per rate (the BENCH block)
    assert rep.slo["diagnosis"]["windows"][0]["span"][0] == 0


def test_phase_hist_closed_loop_invariants():
    """The phase decomposition's pinned closed-loop identities: the
    consensus row equals lat_hist bucket-for-bucket (admission IS the
    first batch), the queue row is all zero-duration, and commit /
    learn rows count only instances whose ladder/quorum completed."""
    from tpu_paxos.config import FaultConfig, SimConfig
    from tpu_paxos.core import sim

    cfg = SimConfig(
        n_nodes=3, n_instances=16, proposers=(0, 1), seed=0,
        max_rounds=64, faults=FaultConfig(drop_rate=500),
    )
    res, summ, wsum = sim.run_with_telemetry(cfg)
    ph = np.asarray(wsum.phase_hist)
    lat = np.asarray(wsum.lat_hist)
    assert (ph[:, telem.PHASE_CONSENSUS, :] == lat).all()
    assert ph[:, telem.PHASE_QUEUE, 1:].sum() == 0
    assert ph[:, telem.PHASE_QUEUE, 0].sum() == lat.sum()
    assert ph[:, telem.PHASE_LEARN].sum() <= lat.sum()
    assert ph[:, telem.PHASE_COMMIT].sum() <= lat.sum()
    # the ledger stamps come back ordered: batch <= chosen <=
    # learned/committed wherever both exist
    res2, s2, w2, led = sim.run_with_telemetry(cfg, return_ledger=True)
    chosen = res2.chosen_round
    for k in ("learned_round", "committed_round"):
        stamp = led[k]
        ok = (stamp >= 0) & (chosen >= 0)
        assert (stamp[ok] >= chosen[ok]).all()
    ok = (led["batch_round"] >= 0) & (chosen >= 0)
    assert (led["batch_round"][ok] <= chosen[ok]).all()
