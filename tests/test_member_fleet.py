"""Fleet membership lanes (PR 12): the device-resident churn driver
vmapped over (seed x churn-schedule x fault-schedule) lanes, judged on
device by the membership invariant subset.

Contracts: lane-for-lane decision-log parity with single
``ChurnEngine.run`` executions (the threefry-partitionable argument
the sim fleet pinned in PR 4), zero XLA compiles on a warm envelope
dispatch (the PR-5 cache discipline, via
``fleet/envelope.member_runner_for``), and the on-device verdict —
quorum-intersection observable, learner catch-up, crash-excused
coverage — flagging seeded violations while passing clean runs.

The heavier mixed-schedule parity grid is slow-marked; its fast-tier
coverage is ``test_member_fleet_lane_parity_vs_single`` (2 lanes,
same code path) plus test_churn_table.py's single-run parity pins.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_paxos.analysis import tracecount
from tpu_paxos.core import faults as flt
from tpu_paxos.core import values as val
from tpu_paxos.fleet import envelope as env
from tpu_paxos.fleet import member_runner as mrun
from tpu_paxos.membership import churn_table as ctm
from tpu_paxos.membership import engine as meng

N, I = 4, 24
CHURN = ctm.grow_shrink_schedule(4, 2, values_per_step=1)
CHURN2 = ctm.grow_shrink_schedule(3, 1, values_per_step=2)
SCHEDS = [
    None,
    flt.FaultSchedule((flt.pause(4, 9, 2),)),
    flt.FaultSchedule((flt.crash(16, 3), flt.pause(2, 6, 1))),
    flt.FaultSchedule((flt.partition(3, 8, (0, 1), (2, 3)),)),
]


@pytest.fixture(scope="module")
def warm_runner():
    return env.member_runner_for(
        N, I, max_events=16, max_episodes=4, max_rounds=500
    )


def test_member_fleet_lane_parity_vs_single(warm_runner):
    seeds = [0, 3]
    churns = [CHURN, CHURN2]
    scheds = [SCHEDS[1], SCHEDS[2]]
    rep = warm_runner.run(seeds, churns, scheds)
    assert rep.verdict.ok.all(), rep.verdict
    eng = meng.ChurnEngine(
        N, I, runtime_tables=True, max_events=16, max_episodes=4,
        max_rounds=500,
    )
    for i in range(rep.n_lanes):
        single = eng.run(seed=seeds[i], churn=churns[i], schedule=scheds[i])
        assert rep.lane_log(i) == single.decision_log(), f"lane {i}"
        assert int(rep.verdict.rounds[i]) == single.rounds


def test_member_fleet_warm_dispatch_zero_compiles(warm_runner):
    census = tracecount.CompileCensus().start()
    try:
        warm_runner.run([11, 12], [CHURN, CHURN], [None, SCHEDS[3]])
        n = sum(census.counts.values())
    finally:
        census.stop()
    assert n == 0, f"warm member-fleet dispatch compiled {n}x"
    # and the envelope cache hands back the same runner for the key
    again = env.member_runner_for(
        N, I, max_events=16, max_episodes=4, max_rounds=500
    )
    assert again is warm_runner
    other = env.member_runner_for(
        N, I, max_events=8, max_episodes=4, max_rounds=500
    )
    assert other is not warm_runner


def test_member_fleet_lane_shape_validation(warm_runner):
    with pytest.raises(ValueError, match="per lane"):
        warm_runner.run([0, 1], [CHURN], [None, None])
    with pytest.raises(ValueError, match="node 0"):
        warm_runner.run(
            [0], [CHURN], [flt.FaultSchedule((flt.crash(2, 0),))]
        )
    big = ctm.ChurnSchedule(tuple(
        ctm.ChurnEvent(vid=100 + k) for k in range(warm_runner.c - I + 1)
    ))
    with pytest.raises(ValueError, match="lane 0.*pending ring"):
        env.member_runner_for(
            N, I, max_events=len(big.events), max_episodes=4,
            max_rounds=500,
        ).run([0], [big], [None])


# ---------------- verdict true positives + clean ----------------

def _clean_final():
    eng = meng.ChurnEngine(N, I, churn=CHURN, max_rounds=500)
    res = eng.run(seed=1)
    assert res.done
    ctab = ctm.encode_churn(CHURN, N, 16)
    return res.state, jax.tree.map(jnp.asarray, ctab)


def test_member_verdict_clean_state_passes():
    st, ctab = _clean_final()
    v = mrun.member_lane_verdict(st, ctab, jnp.bool_(True))
    assert bool(v.ok) and bool(v.quorum) and bool(v.catchup)
    assert bool(v.coverage) and bool(v.completed)


def test_member_verdict_flags_seeded_quorum_violation():
    """A learner cell disagreeing with the chosen record — what
    non-intersecting epoch quorums would produce — must flip the
    quorum invariant (and only it)."""
    st, ctab = _clean_final()
    k = int(np.flatnonzero(
        np.asarray(st.chosen_vid) != int(val.NONE)
    )[0])
    bad = st._replace(learned=st.learned.at[k, 1].set(999_999))
    v = mrun.member_lane_verdict(bad, ctab, jnp.bool_(True))
    assert not bool(v.quorum) and not bool(v.ok)
    assert bool(v.coverage)


def test_member_verdict_flags_seeded_catchup_violation():
    """A live in-view learner missing a chosen instance (a
    never-drained anti-entropy pull) must flip learner catch-up."""
    st, ctab = _clean_final()
    k = int(np.flatnonzero(
        np.asarray(st.chosen_vid) != int(val.NONE)
    )[0])
    # node 1 is a learner in node 0's final view (shrink keeps {0,1})
    assert bool(np.asarray(st.learners[0])[1])
    bad = st._replace(learned=st.learned.at[k, 1].set(val.NONE))
    v = mrun.member_lane_verdict(bad, ctab, jnp.bool_(True))
    assert not bool(v.catchup) and not bool(v.ok)
    assert bool(v.quorum)


def test_member_verdict_crash_excuses_coverage():
    """Events injected via a node the lane's schedule crashed are
    excused from coverage (the sim fleet's crashed-owner rule); the
    lane still fails on completed=False, so a stalled churn is a
    finding, not a silent pass."""
    churn = ctm.ChurnSchedule((
        ctm.ChurnEvent(vid=300, via=1),
        ctm.ChurnEvent(vid=301, via=1, wait=ctm.WAIT_CHOSEN, t0=30),
    ))
    runner = mrun.MemberFleetRunner(
        N, I, max_events=4, max_episodes=2, max_rounds=60,
    )
    # crash node 1 before its second event can inject: the event is
    # never chosen, but its via-node crash excuses coverage
    rep = runner.run(
        [0], [churn], [flt.FaultSchedule((flt.crash(5, 1),))]
    )
    assert not bool(rep.verdict.completed[0])
    assert bool(rep.verdict.coverage[0])
    assert not bool(rep.verdict.ok[0])
    assert rep.failing == [0]
    # the failing lane's state transfers for triage
    final = rep.lane_state(0)
    assert bool(np.asarray(final.crashed)[1])


@pytest.mark.slow
def test_member_fleet_mixed_grid_parity(warm_runner):
    """Slow tier: the full 4-lane mixed-schedule grid (clean / pause /
    crash+pause / partition) — per-lane decision logs equal the
    single-run twins.  Fast-tier coverage:
    test_member_fleet_lane_parity_vs_single."""
    seeds = [0, 1, 2, 3]
    churns = [CHURN, CHURN, CHURN2, CHURN2]
    rep = warm_runner.run(seeds, churns, SCHEDS)
    assert rep.verdict.ok.all()
    eng = meng.ChurnEngine(
        N, I, runtime_tables=True, max_events=16, max_episodes=4,
        max_rounds=500,
    )
    for i in range(4):
        single = eng.run(
            seed=seeds[i], churn=churns[i], schedule=SCHEDS[i]
        )
        assert rep.lane_log(i) == single.decision_log(), f"lane {i}"
