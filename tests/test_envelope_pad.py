"""Geometry-padded envelopes (core/geom.py): ONE compiled executable
serves every tenant geometry on the menu.

The contract under test, stacked on the runtime-knob parity of
tests/test_knobs.py: an engine built with a ``GeometryEnvelope`` pads
its node/proposer axes to the menu bound, takes the TRUE geometry and
the protocol constants as runtime data, and is decision-log
sha256-IDENTICAL to the bound-free engine per (cfg, schedule, seed) —
the menu-switched PRNG draws (``geo.menu_randint``; threefry bits are
shape-dependent) are the bit-exactness anchor.  Absent nodes are
permanently masked (never sampled, never quorum-counted), and the
envelope cache collapses geometry + protocol out of its key, so a
(geometry x protocol-knob x rate) grid costs dispatches, not compiles
— pinned live by the compile census.
"""

import dataclasses
import hashlib

import numpy as np
import pytest

from tpu_paxos.analysis import tracecount
from tpu_paxos.config import (
    EdgeFaultConfig, FaultConfig, ProtocolConfig, SimConfig,
)
from tpu_paxos.core import faults as flt
from tpu_paxos.core import geom as geo
from tpu_paxos.core import net as netm
from tpu_paxos.fleet import envelope as env
from tpu_paxos.fleet import runner as frun
from tpu_paxos.replay.decision_log import decision_log

#: The fast-tier envelope: 3-node single-proposer tenants padded into
#: a 5-node two-proposer bound.
ENV35 = geo.GeometryEnvelope(menu=((3, (0,)), (5, (0, 1))))
#: The slow-tier envelope: the full 3/5/7 menu of the BENCH sweep.
ENV357 = geo.GeometryEnvelope(
    menu=((3, (0,)), (5, (0, 1)), (7, (0, 1, 2)))
)

#: Workload template (defines the envelope's vid bound and queue
#: capacity) and the true-geometry lane workloads cut from it —
#: per-lane rows must match the template's row length (the envelope's
#: queue-capacity contract), so the 3-node single-proposer lane names
#: ONE row of the same width.
TMPL = [np.arange(100, 108, dtype=np.int32),
        np.arange(200, 208, dtype=np.int32)]
WL3 = [np.arange(100, 108, dtype=np.int32)]
WL5 = TMPL

#: 3-node-safe episode mix (no node past id 2).
SCHED3 = flt.FaultSchedule((
    flt.pause(1, 4, 1),
    flt.burst(5, 10, 1500),
))
#: 5-node mixes: the knob-parity grid's schedule, and a gray/WAN-
#: weather mix with a deterministic crash point.
SCHED5 = flt.FaultSchedule((
    flt.partition(4, 16, (0, 1), (2, 3, 4)),
    flt.pause(6, 14, 2),
    flt.burst(5, 12, 1500),
))
GRAY5 = flt.FaultSchedule((
    flt.partition(2, 8, (0, 1), (2, 3, 4)),
    flt.gray(3, 9, 2, delay=2),
    flt.crash(20, 4),
))


def _cfg(n_nodes, proposers, fkw, seed=3, max_rounds=4000, pc=None):
    return SimConfig(
        n_nodes=n_nodes, n_instances=16, proposers=proposers, seed=seed,
        max_rounds=max_rounds, faults=FaultConfig(**fkw),
        protocol=pc or ProtocolConfig(),
    )


def _log_sha(r):
    stride = int(max(int(np.max(w)) for w in TMPL)) + 1
    text = decision_log(
        r.chosen_vid, r.chosen_ballot, stride=stride,
        n_instances=len(r.chosen_vid),
    )
    return hashlib.sha256(text.encode()).hexdigest()


def _assert_pad_parity(rep_true, rep_pad, n_true):
    """Lane-for-lane: the padded dispatch is decision-log
    sha256-identical AND bit-identical to the bound-free dispatch of
    the same (cfg, schedule, seed); pad nodes never crash and never
    learn."""
    assert rep_true.n_lanes == rep_pad.n_lanes
    for i in range(rep_true.n_lanes):
        a = rep_true.lane_result(i)
        b = rep_pad.lane_result(i)
        assert a.rounds == b.rounds, (i, a.rounds, b.rounds)
        assert _log_sha(a) == _log_sha(b), i
        assert (a.chosen_vid == b.chosen_vid).all(), i
        assert (a.chosen_round == b.chosen_round).all(), i
        # paxlint: allow[JAX103] per-lane bit-compare IS this assert's purpose
        assert (np.asarray(a.learned)
                == np.asarray(b.learned)[:, :n_true]).all(), i  # paxlint: allow[JAX103] per-lane bit-compare IS this assert's purpose
        # paxlint: allow[JAX103] per-lane bit-compare IS this assert's purpose
        assert (np.asarray(a.crashed)
                == np.asarray(b.crashed)[:n_true]).all(), i  # paxlint: allow[JAX103] per-lane bit-compare IS this assert's purpose
        assert not np.asarray(b.crashed)[n_true:].any(), (  # paxlint: allow[JAX103] per-lane bit-compare IS this assert's purpose
            f"lane {i}: a permanently-masked pad node crashed"
        )
        assert a.done == b.done, i
        va, vb = rep_true.verdict, rep_pad.verdict
        for f in ("ok", "agreement", "coverage", "quiescent"):
            assert bool(getattr(va, f)[i]) == bool(getattr(vb, f)[i]), (
                i, f,
            )


# ---------------- decision-log parity ----------------

# The two fleet-padded cells below pay the padded executable's cold
# compile (~70 s on the 2-core CPU box) and are slow-marked per the
# tier-1 budget rule.  Fast-tier coverage of this module:
# test_member_pad_parity_3in5 + test_envelope_named_rejections here,
# the envelope guard cells in tests/test_bench_guards.py, and
# `make envelope-quick` (wired into `make check`) which runs
# test_envelope_compile_collapse by node id regardless of marks.


@pytest.mark.slow
def test_pad_parity_3in5():
    """Fast parity cell: a 3-node single-proposer tenant dispatched
    through the 5-node-bound padded executable vs the bound-free
    3-node build — debug.conf knobs, pause+burst schedule, two seeds.
    The padded runner comes from the ENVELOPE CACHE (the surface every
    consumer actually calls)."""
    fkw = dict(drop_rate=500, dup_rate=1000, max_delay=2)
    cfg3 = _cfg(3, (0,), fkw)
    kn = [cfg3.faults] * 2
    r3 = frun.FleetRunner(cfg3, WL3)
    rep3 = r3.run([3, 5], [SCHED3] * 2,
                  workloads=[(WL3, None)] * 2, knobs=kn)
    # bound-free runners reject padded dispatch inputs by name
    with pytest.raises(ValueError, match="geometry-padded dispatch"):
        r3.run([3], [None], workloads=[(WL3, None)], knobs=kn[:1],
               geometry=(3, (0,)))
    rp = env.runner_for(cfg3, TMPL, geometry=ENV35)
    repp = rp.run([3, 5], [SCHED3] * 2,
                  workloads=[(WL3, None)] * 2, knobs=kn,
                  geometry=(3, (0,)), protocol=cfg3.protocol)
    _assert_pad_parity(rep3, repp, 3)
    # the report replays as the TRUE geometry, not the bound
    assert repp.lane_cfg(0).n_nodes == 3
    assert repp.lane_cfg(0).proposers == (0,)


@pytest.mark.slow
def test_envelope_compile_collapse():
    """The tentpole pin: ONE warm executable serves the whole
    (geometry x protocol-knob x rate) grid — the live compile census
    reads ZERO fleet compiles after the first dispatch.  Also pins the
    cache collapse itself: every true geometry and knob mix of the
    envelope lands on the SAME cached runner object."""
    cfg3 = _cfg(3, (0,), dict(max_delay=2))
    cfg5 = _cfg(5, (0, 1), dict(drop_rate=500, max_delay=4))
    rp = env.runner_for(cfg3, TMPL, geometry=ENV35)
    assert env.runner_for(cfg5, TMPL, geometry=ENV35) is rp
    pc2 = ProtocolConfig(
        prepare_retry_timeout=5, accept_retry_timeout=3,
        commit_retry_timeout=4,
    )
    grid = [
        (gmx, wl, sc, pc, dr)
        for gmx, wl, sc in (
            ((3, (0,)), WL3, SCHED3), ((5, (0, 1)), WL5, SCHED5),
        )
        for pc in (ProtocolConfig(), pc2)
        for dr in (0, 900)
    ]
    census = tracecount.CompileCensus().start()
    first = grid[0]
    gmx, wl, sc, pc, dr = first
    kn = [FaultConfig(max_delay=4, drop_rate=dr, crash_rate=800)] * 2
    rp.run([3, 5], [sc] * 2, workloads=[(wl, None)] * 2, knobs=kn,
           geometry=gmx, protocol=pc)
    warm = census.engine_counts.get("fleet", 0)
    for gmx, wl, sc, pc, dr in grid[1:]:
        kn = [FaultConfig(max_delay=4, drop_rate=dr, crash_rate=800)] * 2
        rp.run([3, 5], [sc] * 2, workloads=[(wl, None)] * 2, knobs=kn,
               geometry=gmx, protocol=pc)
    census.stop()
    assert census.engine_counts.get("fleet", 0) == warm, (
        "a warm grid cell recompiled the fleet executable — the "
        "geometry-padded envelope should serve every cell"
    )


# ---------------- named rejections ----------------


def test_envelope_named_rejections():
    """Every envelope boundary rejects BY NAME: over-bound and
    off-menu geometries, out-of-span protocol knobs, over-bound knob
    matrices and workloads, and runners built off the bound.
    Construction is lazy (jit compiles on first dispatch), so these
    cells cost no executables."""
    with pytest.raises(ValueError, match="exceeds the envelope geometry"):
        ENV35.index_of(9, (0,))
    with pytest.raises(ValueError, match="not in the envelope menu"):
        ENV35.index_of(4, (0,))
    with pytest.raises(ValueError, match="exceeds the envelope geometry"):
        ENV35.index_of_nodes(9)
    with pytest.raises(ValueError, match="outside its declared span"):
        geo.protocol_knobs(ProtocolConfig(), stall_patience=0)
    with pytest.raises(ValueError, match="knob matrix"):
        netm.pad_matrix_knobs(
            netm.matrix_knobs(FaultConfig(max_delay=2), 7), 5
        )
    with pytest.raises(ValueError, match="workload names"):
        frun._pad_geometry_workload([np.arange(3)] * 3, None, 2)
    cfg3 = _cfg(3, (0,), dict(max_delay=2))
    with pytest.raises(ValueError, match="built at the envelope bound"):
        frun.FleetRunner(cfg3, WL3, geometry=ENV35)
    # the cached padded runner rejects a bound-free dispatch shape
    rp = env.runner_for(cfg3, TMPL, geometry=ENV35)
    with pytest.raises(ValueError, match="TRUE geometry per dispatch"):
        rp.run([3], [None], workloads=[(WL3, None)],
               knobs=[FaultConfig()])
    # a directly-built padded runner (no cache guard in front) still
    # demands the true-geometry owner map
    rp_direct = frun.FleetRunner(
        ENV35.bound_cfg(cfg3), TMPL, geometry=ENV35
    )
    with pytest.raises(ValueError, match="needs explicit workloads="):
        rp_direct.run([3], [None], knobs=[FaultConfig()],
                      geometry=(3, (0,)))
    # off-menu dispatches and out-of-span knob mixes, per dispatch
    with pytest.raises(ValueError, match="not in the envelope menu"):
        rp.run([3], [None], workloads=[(WL3, None)],
               knobs=[FaultConfig()], geometry=(4, (0,)))
    with pytest.raises(ValueError, match="outside its declared span"):
        rp.run([3], [None], workloads=[(WL3, None)],
               knobs=[FaultConfig()], geometry=(3, (0,)),
               protocol=ProtocolConfig(prepare_retry_timeout=10_000))
    # member stack: same named boundaries
    from tpu_paxos.fleet import member_runner as mfr

    with pytest.raises(ValueError, match="exceeds the envelope geometry"):
        env.member_runner_for(9, 8, geometry=ENV35)
    with pytest.raises(ValueError, match="envelope node bound"):
        mfr.MemberFleetRunner(3, 8, geometry=ENV35)
    rm = env.member_runner_for(3, 8, max_events=4, geometry=ENV35)
    with pytest.raises(ValueError, match="TRUE node count"):
        rm.run([0], [None], [None])
    rm0 = mfr.MemberFleetRunner(3, 8, max_events=4)
    with pytest.raises(ValueError, match="geometry-padded dispatch"):
        rm0.run([0], [None], [None], n_nodes=3)


# ---------------- membership stack: fast cell ----------------


def test_member_pad_parity_3in5():
    """Membership twin of the fast parity cell: a 3-node churn fleet
    dispatched through the 5-node-bound padded member executable is
    decision-LOG byte-identical to the bound-free build (the member
    engine's only geometry-shaped draws are its backoff and crash
    coins — both menu-switched)."""
    from tpu_paxos.fleet import member_runner as mfr
    from tpu_paxos.membership import churn_table as ctm
    from tpu_paxos.membership import engine as meng

    churns = [
        ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=100),
            ctm.ChurnEvent(
                vid=meng.change_vid(1, meng.ADD_ACCEPTOR),
                wait=ctm.WAIT_CHOSEN,
            ),
        )),
        ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=200),
            ctm.ChurnEvent(vid=201, wait=ctm.WAIT_CHOSEN),
        )),
    ]
    scheds = [flt.FaultSchedule((flt.pause(2, 5, 1),)), None]
    r3 = mfr.MemberFleetRunner(
        3, 8, max_events=4, max_episodes=2, crash_rate=500, max_rounds=64
    )
    rp = env.member_runner_for(
        3, 8, max_events=4, max_episodes=2, crash_rate=500,
        max_rounds=64, geometry=ENV35,
    )
    # cache collapse: both menu geometries land on the same runner
    assert env.member_runner_for(
        5, 8, max_events=4, max_episodes=2, crash_rate=500,
        max_rounds=64, geometry=ENV35,
    ) is rp
    rep3 = r3.run([0, 1], churns, scheds)
    census = tracecount.CompileCensus().start()
    repp = rp.run([0, 1], churns, scheds, n_nodes=3)
    warm = census.engine_counts.get("member", 0)
    repp2 = rp.run([1, 0], churns, scheds, n_nodes=3)
    census.stop()
    assert census.engine_counts.get("member", 0) == warm, (
        "a warm member dispatch recompiled the padded executable"
    )
    assert repp2.n_lanes == 2
    for i in range(2):
        assert rep3.lane_log(i) == repp.lane_log(i), i
        for f in ("ok", "quorum", "catchup", "coverage", "completed"):
            assert (bool(getattr(rep3.verdict, f)[i])
                    == bool(getattr(repp.verdict, f)[i])), (i, f)


# ---------------- decision-log parity: slow grid ----------------


@pytest.mark.slow
def test_pad_parity_5in7_grid():
    """Heavy parity grid, 7-node bound: 5-in-7 and 3-in-7 builds
    across episode mixes (partition+pause+burst, partition+gray+crash,
    schedule-free) x knob tiers (zero, debug.conf) plus a WAN
    edge-matrix cell — every cell decision-log sha256-identical to the
    bound-free build, all through ONE padded executable.

    Slow tier: two bound-free compiles + one 7-bound padded compile
    (~2-3 min).  Fast-tier coverage: test_pad_parity_3in5 pins the
    same parity contract at the 5-node bound, and
    test_envelope_compile_collapse pins the census on the same grid
    shape every tier-1 run."""
    rp = env.runner_for(
        _cfg(7, (0, 1, 2), dict(max_delay=4)), TMPL, geometry=ENV357
    )
    wan = FaultConfig(
        max_delay=4,
        edges=EdgeFaultConfig(
            drop_rate=np.full((5, 5), 300, np.int32),
            dup_rate=np.full((5, 5), 200, np.int32),
            min_delay=np.zeros((5, 5), np.int32),
            max_delay=np.full((5, 5), 3, np.int32),
        ),
    )
    cells5 = [
        (SCHED5, FaultConfig()),
        (SCHED5, FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2,
                             crash_rate=3000)),
        (GRAY5, FaultConfig(drop_rate=300, max_delay=4, crash_rate=800)),
        (None, wan),
    ]
    cfg5 = _cfg(5, (0, 1), dict(max_delay=4))
    r5 = frun.FleetRunner(cfg5, WL5)
    census = tracecount.CompileCensus().start()
    for sched, fc in cells5:
        rep5 = r5.run([3, 5], [sched] * 2,
                      workloads=[(WL5, None)] * 2, knobs=[fc] * 2)
        repp = rp.run([3, 5], [sched] * 2,
                      workloads=[(WL5, None)] * 2, knobs=[fc] * 2,
                      geometry=(5, (0, 1)))
        _assert_pad_parity(rep5, repp, 5)
    # 3-in-7: the same executable, two menu steps below the bound
    cfg3 = _cfg(3, (0,), dict(max_delay=4))
    r3 = frun.FleetRunner(cfg3, WL3)
    rep3 = r3.run([3, 5], [SCHED3] * 2,
                  workloads=[(WL3, None)] * 2,
                  knobs=[FaultConfig(drop_rate=500, max_delay=2)] * 2)
    before = census.engine_counts.get("fleet", 0)
    repp3 = rp.run([3, 5], [SCHED3] * 2,
                   workloads=[(WL3, None)] * 2,
                   knobs=[FaultConfig(drop_rate=500, max_delay=2)] * 2,
                   geometry=(3, (0,)))
    census.stop()
    _assert_pad_parity(rep3, repp3, 3)
    assert census.engine_counts.get("fleet", 0) - before <= 2, (
        "switching true geometry under the padded envelope recompiled"
    )


@pytest.mark.slow
def test_member_pad_parity_5in7():
    """Membership slow cell: a 5-node churn fleet (growth churn +
    pause and crash weather) through the 7-node-bound padded member
    executable, log-identical to the bound-free build.  Fast-tier
    coverage: test_member_pad_parity_3in5 pins the same contract at
    the 5-node bound every tier-1 run."""
    from tpu_paxos.fleet import member_runner as mfr
    from tpu_paxos.membership import churn_table as ctm
    from tpu_paxos.membership import engine as meng

    churns = [
        ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=100),
            ctm.ChurnEvent(
                vid=meng.change_vid(3, meng.ADD_ACCEPTOR),
                wait=ctm.WAIT_CHOSEN,
            ),
            ctm.ChurnEvent(
                vid=meng.change_vid(4, meng.ADD_ACCEPTOR),
                wait=ctm.WAIT_APPLIED,
            ),
        )),
        ctm.ChurnSchedule((
            ctm.ChurnEvent(vid=200),
            ctm.ChurnEvent(vid=201, wait=ctm.WAIT_CHOSEN),
        )),
    ]
    scheds = [
        flt.FaultSchedule((flt.pause(2, 5, 1),)),
        flt.FaultSchedule((flt.crash(8, 2),)),
    ]
    r5 = mfr.MemberFleetRunner(
        5, 8, max_events=4, max_episodes=2, crash_rate=500,
        max_rounds=96,
    )
    rp = env.member_runner_for(
        5, 8, max_events=4, max_episodes=2, crash_rate=500,
        max_rounds=96, geometry=ENV357,
    )
    rep5 = r5.run([0, 1], churns, scheds)
    repp = rp.run([0, 1], churns, scheds, n_nodes=5)
    for i in range(2):
        assert rep5.lane_log(i) == repp.lane_log(i), i
        for f in ("ok", "quorum", "catchup", "coverage", "completed"):
            assert (bool(getattr(rep5.verdict, f)[i])
                    == bool(getattr(repp.verdict, f)[i])), (i, f)


# ---------------- serve stack ----------------


@pytest.mark.slow
def test_serve_pad_parity():
    """Serve-window parity: 3- and 5-node tenants admitted through ONE
    padded donated window executable produce the exact chosen
    (vid, ballot) streams of their bound-free windows, across chained
    dispatches.  Slow tier: three window compiles (~2 min).  Fast-tier
    coverage: the padded round function is the SAME one
    test_pad_parity_3in5 pins (serve windows wrap it), and ``make
    audit`` traces the padded serve window (serve.window_envelope)
    with an HLO golden."""
    import jax.numpy as jnp

    from tpu_paxos.core import sim as simm
    from tpu_paxos.core import values as val
    from tpu_paxos.serve import driver as sdrv
    from tpu_paxos.utils import prng

    def tcfg(n, props):
        return _cfg(n, props, dict(max_delay=2, drop_rate=300), seed=3)

    def run(cfg, wl, geometry=None, gmx=None):
        v = sdrv.vid_bound_of(wl)
        root = prng.root_key(cfg.seed)
        gm = pkn = None
        bcfg = cfg
        if geometry is not None:
            bcfg = geometry.bound_cfg(cfg)
            gm = geo.geometry_for(geometry, *gmx)
            pkn = geo.protocol_knobs(
                cfg.protocol, stall_patience=simm.IDLE_RESTART_ROUNDS
            )
            wl, _ = frun._pad_geometry_workload(
                wl, None, geometry.bound_proposers
            )
        ss, c = sdrv.init_serve_state(
            bcfg, wl, v, root, window_rounds=8,
            geometry=geometry, geom=gm, pknobs=pkn,
        )
        fn = sdrv.window_for(
            bcfg, c, v, 8, window_rounds=8, geometry=geometry
        )
        p = len(bcfg.proposers)
        K, S = 4, 2
        admits = np.full((S, p, K), int(val.NONE), np.int32)
        arrs = np.zeros((S, p, K), np.int32)
        for pi, w in enumerate(wl):
            w = np.asarray(w, np.int32)
            for si in range(S):
                blk = w[si * K:(si + 1) * K]
                admits[si, pi, :len(blk)] = blk
                arrs[si, pi, :len(blk)] = si * 8
        args = (ss, root, jnp.asarray(admits), jnp.asarray(arrs))
        if geometry is not None:
            args = args + (gm, pkn)
        for _ in range(4):
            out = fn(*args)
            ss = out[0]
            args = (ss,) + args[1:]
        return (np.asarray(ss.sim.met.chosen_vid),
                np.asarray(ss.sim.met.chosen_ballot))

    cv3, cb3 = run(tcfg(3, (0,)), WL3)
    cv5u, cb5u = run(tcfg(5, (0, 1)), WL5)
    census = tracecount.CompileCensus().start()
    cv3p, cb3p = run(tcfg(3, (0,)), WL3, geometry=ENV35, gmx=(3, (0,)))
    warm = census.engine_counts.get("serve", 0)
    cv5p, cb5p = run(tcfg(5, (0, 1)), WL5, geometry=ENV35,
                     gmx=(5, (0, 1)))
    census.stop()
    assert (cv3 == cv3p).all() and (cb3 == cb3p).all()
    assert (cv5u == cv5p).all() and (cb5u == cb5p).all()
    assert census.engine_counts.get("serve", 0) == warm, (
        "the second tenant geometry recompiled the serve window"
    )


# ---------------- model checker rides the padded envelope ----------------


@pytest.mark.slow
def test_mc_quick_chunk_padded_byte_equality():
    """The mc quick scope's verdict nibbles are BYTE-IDENTICAL when
    its lanes dispatch through a geometry-padded telemetry runner at
    the 7-node bound — the certified scope is the degenerate case of
    the envelope, not a fork.  One chunk (16 lanes) bounds the cost;
    the full certificate stays pinned by ``make mc-quick`` on the
    bound-free path.  Fast-tier coverage: test_pad_parity_3in5 pins
    the underlying engine parity; the telemetry lane shape is traced
    by ``make audit`` (fleet.run_lanes_telemetry)."""
    from tpu_paxos.analysis import modelcheck as mck
    from tpu_paxos.harness import stress as strs

    scope = mck.load_scopes()["quick"]
    enum = mck.ScopeEnum(scope)
    wl_rng = np.random.default_rng(scope.workload_seed)
    workload, gates, _ = strs._workload(
        scope.proposers, wl_rng, n_ids=scope.n_ids, n_free=scope.n_free
    )
    cfg = SimConfig(
        n_nodes=scope.n_nodes,
        n_instances=2 * sum(len(w) for w in workload),
        proposers=tuple(range(scope.proposers)),
        seed=0,
        max_rounds=scope.max_rounds,
    )
    max_eps = max(scope.max_episodes, frun.MAX_EPISODES)
    genv = geo.GeometryEnvelope(menu=((5, (0, 1)), (7, (0, 1, 2))))
    r0 = env.runner_for(
        cfg, workload, gates, max_episodes=max_eps, telemetry=True
    )
    rp = env.runner_for(
        cfg, workload, gates, max_episodes=max_eps, telemetry=True,
        geometry=genv,
    )
    chunk, n_real = mck.chunk_pad(enum.reduced, scope.chunk_lanes)[0]
    scenarios = [enum.decode(i) for i in chunk]
    seeds = [scope.seeds[sc.seed] for sc in scenarios]
    scheds = [enum.schedule_of(sc) for sc in scenarios]
    wls = [
        (workload, gates if scope.gate_tiers[sc.gate] else None)
        for sc in scenarios
    ]
    kns = [enum.faults_of(sc) for sc in scenarios]
    rep0 = r0.run(seeds, scheds, workloads=wls, knobs=kns)
    repp = rp.run(seeds, scheds, workloads=wls, knobs=kns,
                  geometry=(cfg.n_nodes, cfg.proposers))

    def nibbles(rep):
        v = rep.verdict
        return "".join(
            f"{(bool(v.ok[i]) << 3) | (bool(v.agreement[i]) << 2) | (bool(v.coverage[i]) << 1) | bool(v.quiescent[i]):x}"
            for i in range(n_real)
        )

    assert nibbles(rep0) == nibbles(repp)
