"""Record/replay diff for a WALL-CLOCK-paced membership driver — the
``make replay-diff-member`` body (ref member/diff.sh:1-3 diffs two
runs' logs; member/run.sh:10-16 is the record-then-replay loop).

The driver below paces its injections by real time (sleeps between
marks), so WHICH engine round each proposal/membership change lands on
varies run to run with machine load — exactly the host nondeterminism
the reference's Indet subsystem records (member/indet.cpp:24-119).
The injection log captures the schedule that actually happened; the
replay re-executes it and must produce a byte-identical decision log.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402

# Env-var platform selection is too late (axon sitecustomize); switch
# through jax.config like tests/conftest.py.
# paxlint: allow[DET004] platform selection, value-neutral
jax.config.update("jax_platforms", "cpu")

from tpu_paxos.membership.engine import MemberSim  # noqa: E402


def wall_clock_driver(seed: int) -> MemberSim:
    """Inject proposals + a membership change at ~15 ms wall-clock
    marks while the engine free-runs — the round each lands on depends
    on real time, not on anything deterministic."""
    ms = MemberSim(n_nodes=5, n_instances=64, seed=seed)
    plan = [
        ("propose", 0, 100),
        ("add", 1),
        ("propose", 1, 101),
        ("add", 2),
        ("propose", 0, 102),
    ]
    next_mark = time.monotonic() + 0.015
    while plan or not all(ms.chosen(v) for v in (100, 101, 102)):
        ms.run_rounds(1)
        if plan and time.monotonic() >= next_mark:
            kind, *args = plan.pop(0)
            if kind == "propose":
                ms.propose(args[0], args[1])
            else:
                ms.add_acceptor(args[0])
            next_mark = time.monotonic() + 0.015
        if int(ms.state.t) > 4000:
            raise RuntimeError("driver did not converge")
    return ms


def main() -> None:
    ms = wall_clock_driver(seed=11)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "injections.json")
        ms.save_injections(path)
        ms2 = MemberSim.replay(path)
        rec, rep = ms.decision_log(), ms2.decision_log()
        ok = rec == rep
        print(
            json.dumps(
                {
                    "replay_diff_member": ok,
                    "rounds": int(ms.state.t),
                    "injections": len(ms.injections),
                    "log_bytes": len(rec),
                }
            )
        )
        if not ok:
            sys.exit(1)


if __name__ == "__main__":
    main()
