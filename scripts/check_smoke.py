"""Un-jitted op-by-op smoke of one tiny config per engine — the analog
of the reference's valgrind pass (ref multi/val.sh:1-5, multi/gdb.sh):
run the same program under a slower, stricter execution mode and
require the same invariants.  Driven by ``make check`` with
JAX_DISABLE_JIT=1 (op-by-op eager execution: every lax.cond branch
predicate, dynamic-slice bound, and dtype actually materializes) and
JAX_DEBUG_NANS=1.

Tiny configs on purpose: op-by-op execution re-traces every round.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax  # noqa: E402

# Env-var platform selection is too late here (the axon sitecustomize
# initializes the backend first); switch through jax.config like
# tests/conftest.py.  Op-by-op through a device tunnel would take
# minutes per round.
# paxlint: allow[DET004] platform selection, value-neutral
jax.config.update("jax_platforms", "cpu")

assert jax.config.jax_disable_jit, "run via make check (JAX_DISABLE_JIT=1)"

import numpy as np  # noqa: E402

from tpu_paxos.config import FaultConfig, SimConfig  # noqa: E402
from tpu_paxos.core import fast, sim  # noqa: E402
from tpu_paxos.harness import validate  # noqa: E402
from tpu_paxos.membership.engine import MemberSim  # noqa: E402


def smoke_sim() -> None:
    # fault-free single proposer: ~10 rounds — op-by-op execution pays
    # per-op dispatch for every round, so the round count is the budget
    r = sim.run(
        SimConfig(
            n_nodes=3,
            n_instances=4,
            proposers=(0,),
            seed=0,
            max_rounds=60,
            faults=FaultConfig(),
        )
    )
    assert r.done, f"sim smoke did not quiesce in {r.rounds} rounds"
    validate.check_agreement(r.learned)
    validate.check_exactly_once(r.learned, r.expected_vids)
    print(f"  sim: done in {r.rounds} rounds, all invariants green")


def smoke_fast() -> None:
    n, i = 3, 16
    state = fast.init_state(i, n)
    import jax.numpy as jnp

    state, n_chosen = fast.choose_all(
        state, jnp.arange(i, dtype=jnp.int32), proposer=0, quorum=2
    )
    n_chosen = int(n_chosen)
    assert n_chosen == i, f"fast smoke chose {n_chosen}/{i}"
    print(f"  fast: {n_chosen}/{i} chosen")


def smoke_member() -> None:
    ms = MemberSim(n_nodes=3, n_instances=8, seed=0)
    ms.propose(0, 100)
    assert ms.run_until(lambda: ms.chosen(100), max_rounds=400)
    cv = ms.add_acceptor(1)
    assert ms.run_until(lambda: ms.applied(cv), max_rounds=400)
    print(f"  member: value chosen + membership change applied, t={int(ms.state.t)}")


def smoke_churn() -> None:
    # the device-resident churn driver, op by op: the injection
    # gate's index clamps, the guarded pending-ring scatter, and the
    # run-complete cond all materialize eagerly here
    from tpu_paxos.membership import churn_table as ctm
    from tpu_paxos.membership.engine import ChurnEngine

    eng = ChurnEngine(
        3, 8, churn=ctm.grow_shrink_schedule(3, 2), max_rounds=120,
    )
    res = eng.run(seed=0)
    assert res.done, f"churn smoke stalled at t={res.rounds}"
    print(f"  churn: {res.injected} events driven on device, t={res.rounds}")


if __name__ == "__main__":
    print("check: un-jitted smoke (JAX_DISABLE_JIT=1)")
    smoke_sim()
    smoke_fast()
    smoke_member()
    smoke_churn()
    print("check: OK")
