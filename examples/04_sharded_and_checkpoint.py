"""Scale-out + checkpoint as a library: run the general engine sharded
over every visible device, checkpoint mid-run, and resume
bit-identically.  Works on any backend; for a multi-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/04_sharded_and_checkpoint.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import tempfile

import numpy as np

from tpu_paxos import checkpoint
from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim
from tpu_paxos.harness import validate
from tpu_paxos.parallel import mesh as pmesh
from tpu_paxos.parallel import sharded_sim

mesh = pmesh.make_instance_mesh()
cfg = SimConfig(
    n_nodes=5,
    n_instances=256 - 256 % mesh.size,
    proposers=(0, 1),
    seed=1,
    faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
)
r = sharded_sim.run_sharded(cfg, mesh)
assert r.done
validate.check_all(r.learned, r.expected_vids)
print(f"sharded over {mesh.size} device(s): {r.rounds} rounds, green")

# checkpoint/resume (unsharded engine; any state pytree works)
workload = sim.default_workload(cfg)
pend, gate, tail, c = sim.prepare_queues(cfg, workload)
from tpu_paxos.utils import prng

root = prng.root_key(cfg.seed)
state = sim.init_state(cfg, pend, gate, tail, root)
round_fn = sim.build_engine(cfg, c)
for _ in range(4):  # a few rounds, then snapshot
    state = round_fn(root, state)
with tempfile.TemporaryDirectory() as d:
    path = f"{d}/mid_run"
    checkpoint.save(path, state)
    restored, _meta = checkpoint.restore(path, state)
    a = sim.run_state(cfg, state, root, np.unique(np.concatenate(workload)), c)
    b = sim.run_state(
        cfg, restored, root, np.unique(np.concatenate(workload)), c
    )
    assert (a.chosen_vid == b.chosen_vid).all() and a.rounds == b.rounds
print("checkpoint at round 4 resumed bit-identically")
