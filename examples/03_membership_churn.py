"""Live membership change as a library: grow a 1-node cluster to 5
acceptors through the log while client values flow, then verify
prefix consistency (the member/ variant's core property).

    python examples/03_membership_churn.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tpu_paxos.harness import validate
from tpu_paxos.membership import MemberSim

ms = MemberSim(n_nodes=5, n_instances=64, seed=3)

vid = 100
for target in range(1, 5):
    # a client value and a membership change race through the log
    ms.propose(0, vid)
    change = ms.add_acceptor(target)
    assert ms.run_until(lambda: ms.applied(change), max_rounds=3000)
    vid += 1

assert ms.run_until(
    lambda: all(ms.chosen(v) for v in range(100, vid)), max_rounds=3000
)
validate.check_prefix_consistency([ms.applied_log(i) for i in range(5)])
print(
    f"grew to {len(ms.acceptor_set(0))} acceptors with values in flight; "
    f"prefix consistency green"
)
