"""Crash-rejoin durability + host-injection replay as a library: a
node fail-stops, the cluster keeps going, the node restores from its
checkpoint and catches up through anti-entropy — and the whole
wall-clock-paced scenario replays bit-identically from its recorded
injection log (both beyond the reference, which persists nothing and
aborts on any crash).

    python examples/05_crash_rejoin_replay.py
"""

import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tpu_paxos import checkpoint
from tpu_paxos.harness import validate
from tpu_paxos.membership import MemberSim

ms = MemberSim(n_nodes=5, n_instances=64, seed=9)

# grow to three acceptors, commit a value
for target in (1, 2):
    change = ms.add_acceptor(target)
    assert ms.run_until(lambda: ms.applied(change), max_rounds=3000)
ms.propose(0, 100)
assert ms.run_until(lambda: ms.chosen(100))

with tempfile.TemporaryDirectory() as d:
    # node 2 fail-stops; snapshot its (frozen) durable state — the
    # restart artifact a real deployment keeps on disk
    ms.crash(2)
    ck = os.path.join(d, "node2.npz")
    checkpoint.save(ck, ms.state, meta={"crashed_node": 2})

    # progress continues on the surviving majority
    for v in (101, 102):
        ms.propose(0, v)
        assert ms.run_until(lambda: ms.chosen(v))

    # restart: restore from the checkpoint, rejoin, catch up
    ms.rejoin_from_checkpoint(2, ck)
    assert ms.run_until(
        lambda: {100, 101, 102} <= set(ms.applied_log(2).tolist()),
        max_rounds=3000,
    )
    validate.check_prefix_consistency([ms.applied_log(i) for i in range(5)])
    print(
        f"node 2 rejoined from its checkpoint and caught up "
        f"({len(ms.applied_log(2))} values applied); prefix consistency green"
    )

    # the recorded injection schedule replays the entire scenario —
    # crash, rejoin, and all — bit-identically
    inj = os.path.join(d, "injections.json")
    ms.save_injections(inj)
    replayed = MemberSim.replay(inj)
    assert replayed.decision_log() == ms.decision_log()
    print("recorded run and replay decision logs are byte-identical")
