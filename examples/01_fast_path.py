"""Fast path as a library: drive a batch of instances to chosen and
validate the result.

    python examples/01_fast_path.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax.numpy as jnp
import numpy as np

from tpu_paxos.core import fast
from tpu_paxos.harness import validate

N_NODES = 5
N_INSTANCES = 1 << 16

state = fast.init_state(N_INSTANCES, N_NODES)
vids = jnp.arange(N_INSTANCES, dtype=jnp.int32)  # one value per instance
state, n_chosen = fast.choose_all_jit(
    state, vids, proposer=0, quorum=N_NODES // 2 + 1
)
assert int(n_chosen) == N_INSTANCES

# every node agrees, every value chosen exactly once
validate.check_all(fast.learned_ia(state), np.arange(N_INSTANCES))
print(f"{int(n_chosen)} instances chosen, invariants green")
