"""The general engine as a library: dueling proposers, an in-order
client chain, and the reference's debug.conf fault rates.

    python examples/02_faulty_run.py
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

from tpu_paxos.config import FaultConfig, SimConfig
from tpu_paxos.core import sim
from tpu_paxos.core import values as val
from tpu_paxos.harness import validate

cfg = SimConfig(
    n_nodes=5,
    n_instances=64,
    proposers=(0, 1),  # two dueling proposers
    seed=7,
    faults=FaultConfig(drop_rate=500, dup_rate=1000, max_delay=2),
)

# proposer 0: an in-order chain (each value gated on the previous one
# being chosen); proposer 1: independent values
chain = np.arange(100, 108, dtype=np.int32)
chain_gates = np.asarray([int(val.NONE)] + chain[:-1].tolist(), np.int32)
free = np.arange(200, 212, dtype=np.int32)
workload = [chain, free]
gates = [chain_gates, np.full(len(free), int(val.NONE), np.int32)]

r = sim.run(cfg, workload, gates)
assert r.done, f"no quiescence in {r.rounds} rounds"

seqs = validate.check_all(r.learned, np.concatenate(workload))
validate.check_in_order_clients(max(seqs, key=len), [chain])
print(
    f"quiesced in {r.rounds} rounds; "
    f"{int((r.chosen_vid >= 0).sum())} real values chosen; "
    f"chain executed in order; invariants green"
)
print("value 104 lifecycle:", r.value_status(104))
